"""Durable multi-rank commit protocol for snapshot directories.

Layout of one snapshot (one per checkpointed policy step)::

    <ckpt_root>/
      step_000000001024/
        shard_r00000.pkl        # rank 0's host state tree (pickle)
        shard_r00000.meta.json  # {crc32, bytes} for that shard
        shard_r00001.pkl
        shard_r00001.meta.json
        MANIFEST.json           # step, world size, per-shard crc32/bytes
        COMMIT                  # empty marker, LAST write of the protocol

Every write is tmp-file + fsync + rename + dir-fsync (serialize.durable_write),
and the ``COMMIT`` marker lands only after rank 0 has observed every shard's
meta file — so :func:`latest_checkpoint` (which only ever considers
directories containing ``COMMIT``) can never select a torn snapshot, no
matter where a preemption or power loss interrupts the sequence.

Rank coordination is filesystem-based on purpose: shards are written by
background threads (see ``writer.py``) where collective ops are off-limits
(the fabric's collective sequence numbers assume lockstep main-thread
calls), and TPU fleets checkpoint to shared storage anyway.  Rank 0 polls
for the other ranks' meta files with a timeout; on timeout the snapshot is
simply left uncommitted — invisible to resume, reclaimed by GC later.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from sheeprl_tpu.checkpoint.serialize import (
    dump_bytes,
    durable_write,
    from_host_tree,
    fsync_dir,
)

COMMIT_FILE = "COMMIT"
MANIFEST_FILE = "MANIFEST.json"
STEP_PREFIX = "step_"
SHARED_ROOT_PROBE = ".shared_root_probe"
SHARED_ROOT_ERROR = (
    "checkpoint.root must be shared storage (GCS/NFS) for multi-host runs"
)


def step_dir_name(step: int) -> str:
    return f"{STEP_PREFIX}{int(step):012d}"


def shard_name(rank: int) -> str:
    return f"shard_r{int(rank):05d}.pkl"


def _meta_name(rank: int) -> str:
    return f"shard_r{int(rank):05d}.meta.json"


def _shard_rank(name: str) -> Optional[int]:
    """Rank encoded in a shard file name (None if not a shard name)."""
    if name.startswith("shard_r") and name.endswith(".pkl"):
        try:
            return int(name[len("shard_r"):-len(".pkl")])
        except ValueError:
            return None
    return None


def write_shared_root_probe(root: Union[str, os.PathLike]) -> Path:
    """Rank 0's half of the shared-filesystem validation: durably drop a
    probe marker at the checkpoint root.  Cheap and idempotent — called at
    manager/pod startup, long before the first shard write."""
    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    probe = root / SHARED_ROOT_PROBE
    durable_write(probe, json.dumps({"time": time.time(), "pid": os.getpid()}).encode())
    return probe


def probe_shared_root(
    root: Union[str, os.PathLike], rank: int, timeout_s: float = 60.0
) -> None:
    """Rank >0's half: fail FAST and CLEARLY when ``root`` is not shared
    storage.  Without this, a per-host local ``checkpoint.root`` surfaces
    only as rank 0's bare ``wait_for_shards`` timeout minutes later (rank
    >0's shards land on a disk rank 0 can never see)."""
    if int(rank) == 0:
        return
    probe = Path(root) / SHARED_ROOT_PROBE
    deadline = time.monotonic() + float(timeout_s)
    while not probe.exists():
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"{SHARED_ROOT_ERROR}: rank {rank} waited {timeout_s:g}s at "
                f"{Path(root)} for rank 0's probe marker and it never appeared "
                "(each host is writing to its own private directory)"
            )
        time.sleep(0.1)


def checkpoint_step(step_dir: Union[str, os.PathLike]) -> int:
    """Policy step encoded in a snapshot directory name (-1 if not one)."""
    name = Path(step_dir).name
    if not name.startswith(STEP_PREFIX):
        return -1
    try:
        return int(name[len(STEP_PREFIX):])
    except ValueError:
        return -1


def write_shard(
    step_dir: Union[str, os.PathLike], rank: int, host_state: Any
) -> Dict[str, int]:
    """Durably write one rank's shard + its meta sidecar.  The meta file is
    written AFTER the shard, so its presence implies a complete shard."""
    from sheeprl_tpu.resilience.faults import fault_bytes

    step_dir = Path(step_dir)
    payload, crc = dump_bytes(host_state)
    # chaos-drill injection site: raise/hang simulates a dying disk, while
    # corrupt/truncate damages the payload AFTER the CRC was taken — exactly
    # the bit-rotted/short shard verify_checkpoint must catch downstream
    # (the meta keeps the intended size/CRC, as a real torn write would)
    meta = {"crc32": crc, "bytes": len(payload)}
    payload = fault_bytes("checkpoint.write_shard", payload)
    durable_write(step_dir / shard_name(rank), payload)
    durable_write(step_dir / _meta_name(rank), json.dumps(meta).encode())
    return meta


def wait_for_shards(
    step_dir: Union[str, os.PathLike], world: int, timeout_s: float = 300.0
) -> Optional[Dict[str, Dict[str, int]]]:
    """Poll until every rank's meta file exists; return {shard_name: meta}
    or None on timeout (the snapshot then stays uncommitted)."""
    step_dir = Path(step_dir)
    deadline = time.monotonic() + timeout_s
    while True:
        missing = [r for r in range(world) if not (step_dir / _meta_name(r)).exists()]
        if not missing:
            break
        if time.monotonic() >= deadline:
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint: %s still missing shards from ranks %s after %gs "
                "(snapshot stays uncommitted). If those ranks run on other "
                "hosts, check that %s",
                step_dir.name,
                missing,
                timeout_s,
                SHARED_ROOT_ERROR,
            )
            return None
        time.sleep(0.05)
    shards: Dict[str, Dict[str, int]] = {}
    for r in range(world):
        with open(step_dir / _meta_name(r)) as f:
            shards[shard_name(r)] = json.load(f)
    return shards


def write_commit(
    step_dir: Union[str, os.PathLike],
    step: int,
    world: int,
    timeout_s: float = 300.0,
    extra: Optional[Dict[str, Any]] = None,
) -> bool:
    """Rank 0's side of the protocol: wait for all shards, write the CRC
    manifest, then the ``COMMIT`` marker.  Returns False on shard timeout
    (snapshot left uncommitted — never eligible for resume)."""
    from sheeprl_tpu.resilience.faults import fault_point

    step_dir = Path(step_dir)
    shards = wait_for_shards(step_dir, world, timeout_s)
    if shards is None:
        return False
    # chaos-drill injection site: a crash/hang HERE (after the shards, before
    # the COMMIT marker) is the canonical torn snapshot — it must stay
    # invisible to resume/serve forever
    fault_point("checkpoint.commit")
    manifest = {
        "step": int(step),
        "world": int(world),
        "time": time.time(),
        "shards": shards,
    }
    if extra:
        manifest.update(extra)
    durable_write(step_dir / MANIFEST_FILE, json.dumps(manifest, indent=1).encode())
    durable_write(step_dir / COMMIT_FILE, b"")
    return True


def is_committed(step_dir: Union[str, os.PathLike]) -> bool:
    return (Path(step_dir) / COMMIT_FILE).exists()


def read_manifest(step_dir: Union[str, os.PathLike]) -> Dict[str, Any]:
    with open(Path(step_dir) / MANIFEST_FILE) as f:
        return json.load(f)


def verify_checkpoint(step_dir: Union[str, os.PathLike]) -> List[str]:
    """Re-read every shard and check its CRC against the manifest.  Returns
    the list of problems (empty == intact)."""
    step_dir = Path(step_dir)
    problems: List[str] = []
    if not is_committed(step_dir):
        return [f"{step_dir}: no {COMMIT_FILE} marker"]
    try:
        manifest = read_manifest(step_dir)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{step_dir}: unreadable manifest ({e})"]
    shards = manifest.get("shards", {})
    world = int(manifest.get("world", len(shards)) or len(shards))
    listed = {_shard_rank(n) for n in shards}
    unlisted = [r for r in range(world) if r not in listed]
    if unlisted:
        problems.append(
            f"manifest world={world} but shards for ranks {unlisted} are not "
            "listed (commit raced a partial shard set?)"
        )
    for name, meta in shards.items():
        shard = step_dir / name
        rank = _shard_rank(name)
        tag = f"{name} (rank {rank})" if rank is not None else name
        if not shard.exists():
            problems.append(f"{tag}: missing")
            continue
        data = shard.read_bytes()
        if len(data) != meta["bytes"]:
            problems.append(f"{tag}: {len(data)} bytes, manifest says {meta['bytes']}")
        elif (zlib.crc32(data) & 0xFFFFFFFF) != meta["crc32"]:
            problems.append(f"{tag}: CRC mismatch")
    return problems


CORRUPT_SUFFIX = ".corrupt"


def quarantine_checkpoint(step_dir: Union[str, os.PathLike]) -> Optional[Path]:
    """Atomically rename a damaged COMMITTED snapshot out of the discovery
    namespace: ``step_000…N`` → ``step_000…N.corrupt`` (the suffix makes
    :func:`checkpoint_step` return -1, so ``list_checkpoints`` /
    ``latest_checkpoint`` / ``newer_checkpoint`` — and through them
    ``resume_from=auto`` and the serving loader/watcher — simply never see
    it again).  The data is kept for forensics, not deleted.  Returns the
    quarantine path, or None when the snapshot vanished concurrently (e.g.
    a racing ``gc_checkpoints``) or the rename failed."""
    from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

    step_dir = Path(step_dir)
    target = step_dir.with_name(step_dir.name + CORRUPT_SUFFIX)
    if target.exists():  # quarantined twice (concurrent verifiers)
        suffix = 1
        while target.exists():
            target = step_dir.with_name(f"{step_dir.name}{CORRUPT_SUFFIX}.{suffix}")
            suffix += 1
    try:
        os.replace(step_dir, target)
    except OSError:
        return None
    try:
        fsync_dir(step_dir.parent)
    except OSError:
        pass
    RESILIENCE_MONITOR.record_quarantine(target)
    return target


def verify_or_quarantine(step_dir: Union[str, os.PathLike]) -> List[str]:
    """:func:`verify_checkpoint`, and on any problem quarantine the snapshot
    (committed ones only — torn snapshots are already invisible).  Returns
    the problem list (empty == intact, snapshot untouched)."""
    step_dir = Path(step_dir)
    problems = verify_checkpoint(step_dir)
    if problems and is_committed(step_dir):
        quarantined = quarantine_checkpoint(step_dir)
        if quarantined is not None:
            problems = [*problems, f"quarantined to {quarantined}"]
    return problems


def list_checkpoints(
    root: Union[str, os.PathLike], committed_only: bool = True
) -> List[Path]:
    """Snapshot directories under ``root``, sorted by ascending step."""
    root = Path(root)
    if not root.is_dir():
        return []
    dirs = [d for d in root.iterdir() if d.is_dir() and checkpoint_step(d) >= 0]
    if committed_only:
        dirs = [d for d in dirs if is_committed(d)]
    return sorted(dirs, key=checkpoint_step)


def latest_checkpoint(root: Union[str, os.PathLike]) -> Optional[Path]:
    """Newest COMMITTED snapshot under ``root`` (a ``<log_dir>/checkpoint``
    directory), or None.  Uncommitted (torn) snapshots are never returned."""
    ckpts = list_checkpoints(root, committed_only=True)
    return ckpts[-1] if ckpts else None


def newer_checkpoint(
    root: Union[str, os.PathLike], after_step: int
) -> Optional[Path]:
    """Newest COMMITTED snapshot under ``root`` with step > ``after_step``,
    or None — the serving layer's commit-watch primitive: a hot-reload
    watcher polls this with the step it is currently serving, and a non-None
    return is exactly one durable, fully-committed snapshot to swap to
    (torn snapshots are invisible here by construction)."""
    newest = latest_checkpoint(root)
    if newest is not None and checkpoint_step(newest) > int(after_step):
        return newest
    return None


def wait_for_commit(
    root: Union[str, os.PathLike],
    after_step: int,
    timeout_s: float,
    poll_s: float = 0.1,
) -> Optional[Path]:
    """Block until a snapshot newer than ``after_step`` is committed under
    ``root`` (polling the COMMIT markers), or return None on timeout.
    Test/tooling convenience over :func:`newer_checkpoint`."""
    deadline = time.monotonic() + timeout_s
    while True:
        found = newer_checkpoint(root, after_step)
        if found is not None:
            return found
        if time.monotonic() >= deadline:
            return None
        time.sleep(poll_s)


def load_step_dir(step_dir: Union[str, os.PathLike], rank: int = 0) -> Any:
    """Load one rank's state from a committed snapshot directory.  Falls
    back to shard 0 when this rank has no shard (e.g. resuming a 2-process
    run single-process: replicated params/opt state live in every shard)."""
    import pickle

    step_dir = Path(step_dir)
    if not is_committed(step_dir):
        raise FileNotFoundError(
            f"checkpoint {step_dir} has no {COMMIT_FILE} marker — it is a torn "
            "snapshot (interrupted save) and cannot be resumed from"
        )
    shard = step_dir / shard_name(rank)
    if not shard.exists():
        shard = step_dir / shard_name(0)
    with open(shard, "rb") as f:
        return from_host_tree(pickle.load(f))


def gc_checkpoints(
    root: Union[str, os.PathLike],
    keep_last: Optional[int],
    keep_every: Optional[int] = None,
) -> List[Path]:
    """Retention: delete committed snapshots beyond the newest ``keep_last``,
    except those whose step is a multiple of ``keep_every`` (policy steps) —
    the keep-last-N + keep-every-K policy.  Uncommitted snapshots older than
    the newest committed one are torn leftovers and are removed too.
    Returns the deleted directories.  ``keep_last`` in (None, 0, -1) keeps
    everything (GC fully disabled, including torn-snapshot cleanup)."""
    root = Path(root)
    if keep_last is None or keep_last <= 0:
        return []
    committed = list_checkpoints(root, committed_only=True)
    victims = committed[:-keep_last] if keep_last else []
    if keep_every and keep_every > 0:
        victims = [d for d in victims if checkpoint_step(d) % keep_every != 0]
    if committed:
        newest = checkpoint_step(committed[-1])
        victims += [
            d
            for d in list_checkpoints(root, committed_only=False)
            if not is_committed(d) and checkpoint_step(d) < newest
        ]
    deleted = []
    for d in victims:
        try:
            shutil.rmtree(d)
            deleted.append(d)
        except OSError:
            pass
    if deleted:
        fsync_dir(root)
    return deleted
