"""Checkpoint orchestration: cadence, async saves, commit, retention, resume.

One :class:`CheckpointManager` per training run, created by the fabric the
first time a train loop binds its ``log_dir`` (``fabric.checkpoint_manager``).
The manager owns:

* the **cadence decision** (``checkpoint.every`` policy steps, the final
  ``save_last`` save, and any pending preemption — see ``preemption.py``);
* the **save path**: snapshot on the caller thread, shard write + commit on
  the :class:`~sheeprl_tpu.checkpoint.writer.AsyncCheckpointWriter` thread
  (``checkpoint.async_save=True``, the default) or inline + barrier for the
  synchronous cases (preemption finals, ``async_save=False``);
* **retention**: keep-last-N (``checkpoint.keep_last``) plus keep-every-K
  policy steps (``checkpoint.keep_every``), applied by rank 0 after each
  commit;
* **resume discovery**: :func:`resolve_auto_resume` scans every run under
  the experiment root for the newest committed snapshot
  (``checkpoint.resume_from=auto``).

Rank protocol: every rank saves its OWN shard (its replay-buffer state is
rank-local); rank 0 additionally waits for all shards and writes the
manifest + ``COMMIT`` marker (see ``protocol.py``).
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict, Optional, Union

from sheeprl_tpu.checkpoint.preemption import PREEMPTION_GUARD
from sheeprl_tpu.checkpoint.protocol import (
    gc_checkpoints,
    latest_checkpoint,
    probe_shared_root,
    step_dir_name,
    write_commit,
    write_shard,
    write_shared_root_probe,
)
from sheeprl_tpu.checkpoint.serialize import snapshot_tree, to_host_tree
from sheeprl_tpu.checkpoint.writer import AsyncCheckpointWriter
from sheeprl_tpu.utils.profiler import CHECKPOINT_MONITOR


class CheckpointManager:
    def __init__(self, fabric: Any, cfg: Any, log_dir: Union[str, os.PathLike]):
        ckpt_cfg = cfg.checkpoint if "checkpoint" in cfg else {}
        self.fabric = fabric
        self.every = int(ckpt_cfg.get("every", 0) or 0)
        self.save_last = bool(ckpt_cfg.get("save_last", True))
        self.keep_last = ckpt_cfg.get("keep_last", 5)
        self.keep_every = ckpt_cfg.get("keep_every")
        self.async_save = bool(ckpt_cfg.get("async_save", True))
        self.queue_size = int(ckpt_cfg.get("queue_size", 2) or 2)
        self.commit_timeout_s = float(ckpt_cfg.get("commit_timeout_s", 300.0))
        self.io_retries = int(ckpt_cfg.get("io_retries", 3) or 1)
        self.io_retry_base_s = float(ckpt_cfg.get("io_retry_base_s", 0.5))
        self.hang_warn_s = float(ckpt_cfg.get("hang_warn_s", 120.0) or 0)
        self.preemption_poll_every = int(ckpt_cfg.get("preemption_poll_every", 10) or 10)
        self.save_on_preemption = bool(ckpt_cfg.get("save_on_preemption", True))
        self.root = Path(log_dir) / "checkpoint"
        self._writer: Optional[AsyncCheckpointWriter] = None
        self._guard = PREEMPTION_GUARD
        self._finalized = False
        self._iter = 0
        self._agreed_preempt = False
        # lockstep=False (the pod topology): ranks do NOT call should_save /
        # save in the same iteration, so the collective preemption poll and
        # the post-save barrier are off — agreement arrives over the pod
        # control plane via force_preempt() instead
        self.lockstep = True
        self._probed_shared_root = False
        if fabric.num_processes > 1 and fabric.global_rank == 0:
            # rank 0 drops the shared-root probe marker NOW so rank >0's
            # first save can fail fast when checkpoint.root is host-local
            try:
                write_shared_root_probe(self.root)
            except OSError:
                pass  # surfaced properly by the first real save

    # -- cadence -------------------------------------------------------------
    @property
    def preempted(self) -> bool:
        """Rank-agreed preemption flag.

        Single-process: the local SIGTERM/SIGINT latch directly.
        Multi-process: the flag only flips after :meth:`should_save` has
        exchanged latches across ranks — a signal usually reaches ranks at
        different loop iterations, and a single rank unilaterally entering
        the final save would leave the commit waiting on shards the other
        ranks never write (and desequence the fabric's collectives).
        """
        if self._agreed_preempt:
            return True
        if (self.fabric.num_processes <= 1 or not self.lockstep) and self._guard.requested():
            self._agreed_preempt = True
        return self._agreed_preempt

    def force_preempt(self) -> None:
        """Adopt a preemption decided OUTSIDE the collective poll — the pod
        control plane (an actor cell's latch surfaced by its ``/poll``)
        calls this so the learner enters the same final committed save the
        in-process latch would trigger."""
        self._agreed_preempt = True

    def _poll_preemption(self) -> bool:
        """Latch preemption IN AGREEMENT across ranks: every
        ``checkpoint.preemption_poll_every`` loop iterations all ranks
        all-gather their local latch (the coupled loops call
        :meth:`should_save` in lockstep, so the collective lines up) and
        every rank adopts ``any(latches)`` — they then enter the same final
        synchronous save at the same step, and the commit completes."""
        if self._agreed_preempt:
            return True
        if self.fabric.num_processes <= 1 or not self.lockstep:
            return self.preempted
        if self._iter % self.preemption_poll_every == 0:
            flags = self.fabric.all_gather_object(bool(self._guard.requested()))
            self._agreed_preempt = any(flags)
        return self._agreed_preempt

    def should_save(self, policy_step: int, last_checkpoint: int, final: bool = False) -> bool:
        """The one cadence rule every loop shares: the ``checkpoint.every``
        policy-step interval, the ``save_last`` final save, or a pending
        (rank-agreed) preemption — which must snapshot NOW regardless of
        cadence.

        Polling is also what ARMS the SIGTERM/SIGINT latch (idempotent):
        only loops that read the latch install the handler, so surfaces that
        never poll (dedicated lockstep topologies, the evaluation CLI) keep
        the default one-signal-kills disposition instead of silently
        swallowing the preemption grace window."""
        if self.save_on_preemption:
            self._guard.install()
        self._iter += 1
        if self._poll_preemption():
            return True
        if self.every > 0 and policy_step - last_checkpoint >= self.every:
            return True
        return final and self.save_last

    # -- saving --------------------------------------------------------------
    def step_dir(self, step: int) -> Path:
        return self.root / step_dir_name(step)

    def save(self, step: int, state: Dict[str, Any], sync: Optional[bool] = None) -> Path:
        """Checkpoint ``state`` as this rank's shard of snapshot ``step``.

        The snapshot (device-side copies + host memcpys) happens HERE, on
        the caller thread, so the caller may keep mutating buffers and
        donating params immediately after this returns.  Everything slow —
        fence, ``device_get``, pickle, fsync'd writes, commit, retention —
        runs on the writer thread unless ``sync`` (preemption finals,
        ``checkpoint.async_save=False``).
        """
        if sync is None:
            sync = not self.async_save or self.preempted
        rank = self.fabric.global_rank
        world = self.fabric.num_processes
        step_dir = self.step_dir(step)
        step_dir.mkdir(parents=True, exist_ok=True)
        snap = snapshot_tree(state)

        def job() -> int:
            from sheeprl_tpu.utils.utils import device_sync

            if world > 1 and rank > 0 and not self._probed_shared_root:
                # fail fast with the shared-storage error instead of rank
                # 0's bare wait_for_shards timeout minutes later
                probe_shared_root(self.root, rank, timeout_s=60.0)
                self._probed_shared_root = True
            # true completion fence before the host fetch (PR-1 semantics:
            # block_until_ready resolves at dispatch on the axon tunnel)
            device_sync(snap)
            meta = write_shard(step_dir, rank, to_host_tree(snap))
            if rank == 0:
                committed = write_commit(
                    step_dir, step=step, world=world, timeout_s=self.commit_timeout_s
                )
                if committed:
                    gc_checkpoints(self.root, self.keep_last, self.keep_every)
            return meta["bytes"]

        if sync:
            # a concurrent writer-thread GC/commit must not interleave with
            # the inline job on the same rank: drain first
            if self._writer is not None:
                self._writer.flush()
            from sheeprl_tpu.checkpoint.writer import run_with_io_retry

            t0 = time.perf_counter()
            # same transient-IO tolerance as the async writer: a preemption
            # final save racing a flaky disk should not lose the run
            nbytes = run_with_io_retry(job, self.io_retries, self.io_retry_base_s)
            CHECKPOINT_MONITOR.record_save(
                seconds=time.perf_counter() - t0, nbytes=nbytes, asynchronous=False
            )
            # all ranks leave the save together so no rank races ahead into
            # teardown while rank 0 still waits on its shards (lockstep
            # loops only: pod cells are not in the same iteration, and the
            # commit wait itself is the learner's ordering fence)
            if self.lockstep:
                self.fabric.barrier()
        else:
            if self._writer is None:
                self._writer = AsyncCheckpointWriter(
                    queue_size=self.queue_size,
                    io_retries=self.io_retries,
                    io_retry_base_s=self.io_retry_base_s,
                    hang_warn_s=self.hang_warn_s,
                )
            self._writer.submit(job)
        return step_dir

    # -- resume --------------------------------------------------------------
    def latest(self) -> Optional[Path]:
        return latest_checkpoint(self.root)

    def flush(self) -> None:
        """Drain outstanding async saves WITHOUT finalizing (the rollback
        path needs pending commits on disk, then keeps checkpointing)."""
        if self._writer is not None:
            self._writer.flush()

    # -- lifecycle -----------------------------------------------------------
    def finalize(self, timeout_s: Optional[float] = 300.0) -> None:
        """Drain outstanding async saves (idempotent; call before teardown)."""
        if self._finalized:
            return
        self._finalized = True
        if self._writer is not None:
            self._writer.close(timeout_s)
            self._writer = None


def resolve_auto_resume(
    base: Union[str, os.PathLike],
    root_dir: Union[str, os.PathLike],
    exclude: Any = (),
) -> Optional[Path]:
    """``checkpoint.resume_from=auto``: newest committed snapshot across
    every run/version under ``<base>/<root_dir>`` (run names are usually
    timestamped, so a relaunch gets a FRESH run dir and must look across
    its siblings).  "Newest" is by commit time, not step: step counters
    from unrelated restarts of the same experiment are not comparable."""
    import glob

    from sheeprl_tpu.checkpoint.protocol import checkpoint_step

    root = os.path.join(os.fspath(base), os.fspath(root_dir))
    best: Optional[Path] = None
    best_mtime = -1.0
    for ckpt_root in glob.glob(os.path.join(root, "*", "version_*", "checkpoint")):
        for step_dir in map(Path, glob.glob(os.path.join(ckpt_root, "step_*"))):
            if checkpoint_step(step_dir) < 0:
                continue  # quarantined (step_*.corrupt) snapshots are out
            if step_dir in exclude:
                continue  # known-damaged but un-renameable (read-only store)
            commit = step_dir / "COMMIT"
            try:
                mtime = commit.stat().st_mtime
            except OSError:
                continue  # uncommitted (torn) snapshots are never eligible
            if mtime > best_mtime:
                best, best_mtime = step_dir, mtime
    return best
