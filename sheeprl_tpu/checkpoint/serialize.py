"""Host-side checkpoint serialization primitives.

Three layers, each reusable on its own:

* **Tree capture** — :func:`snapshot_tree` takes a consistent point-in-time
  copy of a live training state WITHOUT blocking on device compute: device
  arrays are copied on-device (an async dispatch — breaking any later
  donation alias) and host numpy arrays are memcpy'd (they keep mutating as
  the env loop runs).  :func:`to_host_tree` then materializes everything to
  host numpy — typed PRNG key arrays (extended dtypes, on which
  ``np.asarray`` chokes) are unwrapped via ``jax.random.key_data`` into a
  :class:`KeyArrayRef` and re-wrapped with ``jax.random.wrap_key_data`` by
  :func:`from_host_tree` on load, so RNG state round-trips bit-exactly.
* **Durable bytes** — :func:`durable_write` is the only way checkpoint bytes
  reach disk: tmp file in the target directory, ``fsync`` of the file BEFORE
  ``os.replace``, ``fsync`` of the parent directory AFTER, so a power loss
  can never leave an empty-but-renamed file behind.
* **Legacy single-file API** — :func:`save_checkpoint` / :func:`load_checkpoint`
  keep the original one-pickle-per-path surface (``fabric.save``, the model
  manager, old ``.ckpt`` files) on top of the same primitives.
  :func:`load_checkpoint` also accepts a committed step DIRECTORY from the
  commit protocol (see ``protocol.py``) and loads the right rank shard.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KeyArrayRef:
    """Pickle-stable stand-in for a typed PRNG key array: the uint32 key
    data plus the impl name (``threefry2x32``, ...) needed to re-wrap it."""

    impl: str
    data: np.ndarray


def _is_key_array(x: Any) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.extended)


def snapshot_tree(tree: Any) -> Any:
    """Point-in-time copy of a live state tree, safe to hand to a writer
    thread while training continues.

    * ``jax.Array`` leaves (including typed PRNG keys): on-device ``.copy()``
      — an asynchronously-dispatched device op, so this does NOT block on
      the training step that produces the value.  The copy also breaks the
      donation alias: the original may be donated to the next jitted update
      while the copy is fetched at leisure.  On backends where
      ``block_until_ready`` is trustworthy (cpu / gpu / local tpu) plain
      arrays are host-fetched HERE instead: ``device_get`` there is a
      memcpy, while the on-device copy route compiles one tiny XLA program
      per distinct leaf shape per process — multi-second overhead for a
      small checkpoint.  Fetching on the caller thread is donation-safe by
      construction (the value is on host before save() returns).
    * numpy leaves: host memcpy (the env loop keeps writing into replay
      storage; the checkpoint must capture THIS step's contents).
    * ``MemmapArray`` leaves: kept as references — their persistence IS the
      backing file (see data/memmap.py), same semantics as the reference.
    * everything else (scalars, strings, small state dicts): passed through;
      pytree mapping already rebuilds fresh containers.
    """
    from sheeprl_tpu.data.memmap import MemmapArray
    from sheeprl_tpu.utils.utils import _untrusted_block_until_ready

    fast_host = not _untrusted_block_until_ready()

    def leaf(x: Any) -> Any:
        if isinstance(x, MemmapArray):
            return x
        if isinstance(x, jax.Array):
            if fast_host and x.is_fully_addressable and not _is_key_array(x):
                # np.array (not asarray): device_get on the CPU backend can
                # be zero-copy, and the caller may donate the original
                # buffer right after save() returns
                return np.array(jax.device_get(x))
            if not x.is_fully_addressable:
                # multi-host arrays: checkpoint state is replicated
                # (params/opt state); copy the process-local replica
                if not x.sharding.is_fully_replicated:
                    raise ValueError(
                        "checkpoint state contains a non-replicated multi-host "
                        "array; only replicated state trees can be snapshotted"
                    )
                return x.addressable_shards[0].data.copy()
            return x.copy()
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x

    return jax.tree.map(
        leaf,
        tree,
        is_leaf=lambda x: isinstance(x, (jax.Array, MemmapArray)),
    )


def to_host_tree(tree: Any) -> Any:
    """Materialize every device leaf to host numpy (blocking).

    Typed PRNG key arrays become :class:`KeyArrayRef` (``np.asarray`` has no
    representation for extended dtypes); :func:`from_host_tree` reverses it.
    """
    from sheeprl_tpu.data.memmap import MemmapArray

    def leaf(x: Any) -> Any:
        if _is_key_array(x):
            return KeyArrayRef(
                impl=str(jax.random.key_impl(x)),
                data=np.asarray(jax.device_get(jax.random.key_data(x))),
            )
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree.map(
        leaf,
        tree,
        is_leaf=lambda x: isinstance(x, (jax.Array, MemmapArray)),
    )


def from_host_tree(tree: Any) -> Any:
    """Re-wrap :class:`KeyArrayRef` leaves into typed PRNG key arrays."""

    def leaf(x: Any) -> Any:
        if isinstance(x, KeyArrayRef):
            return jax.random.wrap_key_data(jnp.asarray(x.data), impl=x.impl)
        return x

    return jax.tree.map(leaf, tree, is_leaf=lambda x: isinstance(x, KeyArrayRef))


def dump_bytes(obj: Any) -> Tuple[bytes, int]:
    """Pickle ``obj`` and return ``(payload, crc32)``."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return payload, zlib.crc32(payload) & 0xFFFFFFFF


def fsync_dir(path: Union[str, os.PathLike]) -> None:
    """fsync a directory so a just-renamed entry survives power loss.  Best
    effort: some filesystems (and all of Windows) refuse O_RDONLY dir fds."""
    try:
        fd = os.open(os.fspath(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_write(path: Union[str, os.PathLike], payload: bytes) -> None:
    """Atomically and durably write ``payload`` to ``path``:
    tmp file in the same directory → flush → ``fsync(file)`` → ``os.replace``
    → ``fsync(parent dir)``.  Without the first fsync a crash after the
    rename can leave a correctly-named EMPTY file (data still in the page
    cache); without the second the rename itself may not be on disk."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(path.parent)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_checkpoint(path: Union[str, os.PathLike], state: Dict[str, Any]) -> int:
    """Legacy single-file save: host-fetch + durable atomic pickle.
    Returns the number of bytes written."""
    payload, _ = dump_bytes(to_host_tree(snapshot_tree(state)))
    durable_write(path, payload)
    return len(payload)


def load_checkpoint(path: Union[str, os.PathLike], rank: int = 0) -> Dict[str, Any]:
    """Load a checkpoint from a legacy ``.ckpt`` file OR a committed step
    directory of the commit protocol (picks the shard for ``rank``).

    ``MemmapArray`` references whose backing files moved hosts rehydrate
    in-memory with a warning instead of raising ``FileNotFoundError`` deep
    inside unpickling (see ``MemmapArray.__setstate__``)."""
    path = Path(path)
    if path.is_dir():
        from sheeprl_tpu.checkpoint.protocol import load_step_dir

        return load_step_dir(path, rank=rank)
    with open(path, "rb") as f:
        return from_host_tree(pickle.load(f))
