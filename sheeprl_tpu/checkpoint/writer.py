"""Background checkpoint writer.

One daemon thread drains a BOUNDED queue of save jobs.  The split of work
between threads is the point of the design:

* **Caller thread** (the train loop): takes the point-in-time snapshot
  (``serialize.snapshot_tree`` — on-device copies dispatched async, host
  memcpys) and enqueues.  Cost: microseconds of dispatch + the host copy,
  never a device sync.
* **Writer thread**: fences the snapshot with ``utils.device_sync`` (the
  PR-1 fence that is trustworthy on the axon tunnel where
  ``block_until_ready`` resolves at dispatch), performs the blocking
  ``jax.device_get``, pickles, CRCs, and writes durably — all overlapped
  with the next update step on the main thread.

The queue is bounded (default 2 in-flight snapshots): if training
checkpoints faster than the disk drains, ``submit`` blocks — back-pressure
instead of unbounded host-memory growth from queued device copies.

A failed job parks its exception and re-raises on the NEXT ``submit`` /
``flush`` so a dying disk cannot silently drop checkpoints for the rest of
a run.  Save timing/bytes are reported into
``utils.profiler.CHECKPOINT_MONITOR`` and surface as ``Checkpoint/*``
metrics through ``utils.metric.flush_metrics``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

from sheeprl_tpu.utils.profiler import CHECKPOINT_MONITOR


class AsyncCheckpointWriter:
    """Single background thread executing checkpoint save jobs in order."""

    def __init__(self, queue_size: int = 2, name: str = "ckpt-writer"):
        self._queue: "queue.Queue[Optional[Callable[[], Any]]]" = queue.Queue(
            maxsize=max(1, int(queue_size))
        )
        self._error: Optional[BaseException] = None
        self._idle = threading.Event()
        self._idle.set()
        # pending counter incremented BEFORE the queue put: relying on
        # queue.unfinished_tasks alone leaves a window between idle.clear()
        # and put() where the worker, finishing the previous job, would see
        # zero unfinished tasks and re-set idle under a queued submit
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            t0 = time.perf_counter()
            try:
                nbytes = job()
                CHECKPOINT_MONITOR.record_save(
                    seconds=time.perf_counter() - t0,
                    nbytes=int(nbytes or 0),
                    asynchronous=True,
                )
            except BaseException as e:  # parked, re-raised on next submit/flush
                self._error = e
                CHECKPOINT_MONITOR.record_error()
            finally:
                self._queue.task_done()
                with self._pending_lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    # -- API -----------------------------------------------------------------
    @property
    def in_flight(self) -> int:
        return self._queue.unfinished_tasks

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    def submit(self, job: Callable[[], Any]) -> None:
        """Enqueue a save job (a callable returning the bytes written).
        Blocks when the bounded queue is full (back-pressure)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        self._raise_pending()
        with self._pending_lock:
            self._pending += 1
            self._idle.clear()
        self._queue.put(job)
        CHECKPOINT_MONITOR.record_depth(self.in_flight)

    def flush(self, timeout_s: Optional[float] = None) -> bool:
        """Wait until every queued job has finished.  Raises a parked writer
        error; returns False only on timeout."""
        done = self._idle.wait(timeout_s)
        self._raise_pending()
        return done

    def close(self, timeout_s: Optional[float] = 300.0) -> None:
        """Drain outstanding jobs and stop the thread (idempotent).  Must
        return within ~``timeout_s`` even when the worker is wedged on a
        dead disk: the sentinel put uses a timeout too — a full bounded
        queue under a stuck worker would otherwise block forever, and the
        daemon thread can simply be abandoned at process exit."""
        if self._closed:
            return
        self._closed = True
        drained = self._idle.wait(timeout_s)
        try:
            self._queue.put(None, timeout=5.0)
        except queue.Full:
            pass  # wedged worker + full queue: abandon the daemon thread
        self._thread.join(timeout=timeout_s if drained else 5.0)
        self._raise_pending()
