"""Background checkpoint writer.

One daemon thread drains a BOUNDED queue of save jobs.  The split of work
between threads is the point of the design:

* **Caller thread** (the train loop): takes the point-in-time snapshot
  (``serialize.snapshot_tree`` — on-device copies dispatched async, host
  memcpys) and enqueues.  Cost: microseconds of dispatch + the host copy,
  never a device sync.
* **Writer thread**: fences the snapshot with ``utils.device_sync`` (the
  PR-1 fence that is trustworthy on the axon tunnel where
  ``block_until_ready`` resolves at dispatch), performs the blocking
  ``jax.device_get``, pickles, CRCs, and writes durably — all overlapped
  with the next update step on the main thread.

The queue is bounded (default 2 in-flight snapshots): if training
checkpoints faster than the disk drains, ``submit`` blocks — back-pressure
instead of unbounded host-memory growth from queued device copies.

Liveness (the resilience layer, docs/resilience.md):

* Transient IO errors (``OSError``) are retried with jittered exponential
  backoff (``checkpoint.io_retries`` attempts) BEFORE the job's exception
  is parked — an NFS blip no longer voids a snapshot.
* A failed job parks its exception and re-raises on the NEXT ``submit`` /
  ``flush`` so a dying disk cannot silently drop checkpoints for the rest
  of a run.
* A :class:`~sheeprl_tpu.resilience.retry.Watchdog` flags a job that has
  made no progress for ``hang_warn_s`` (``Resilience/watchdog_stalls`` + a
  warning) — the first visible symptom of a dead disk, minutes before any
  syscall would error.
* ``close()`` must return even when the worker is wedged mid-syscall on
  dead storage: the drain wait and the thread join are both bounded, and
  an un-joinable worker is ABANDONED with a logged warning (it is a daemon
  thread; interpreter shutdown does not wait for it).

Save timing/bytes are reported into ``utils.profiler.CHECKPOINT_MONITOR``
and surface as ``Checkpoint/*`` metrics through
``utils.metric.flush_metrics``.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from typing import Any, Callable, Optional

from sheeprl_tpu.utils.profiler import CHECKPOINT_MONITOR


def run_with_io_retry(job: Callable[[], Any], attempts: int, base_s: float) -> Any:
    """THE transient-IO retry policy for checkpoint writes — shared by the
    async writer and the manager's synchronous (preemption-final) path so
    the two can never diverge."""
    from sheeprl_tpu.resilience.retry import retry

    return retry(
        job,
        attempts=attempts,
        base_s=base_s,
        max_s=30.0,
        retry_on=(OSError,),
        site="checkpoint.write",
    )


class AsyncCheckpointWriter:
    """Single background thread executing checkpoint save jobs in order."""

    def __init__(
        self,
        queue_size: int = 2,
        name: str = "ckpt-writer",
        io_retries: int = 3,
        io_retry_base_s: float = 0.5,
        hang_warn_s: float = 120.0,
    ):
        self._queue: "queue.Queue[Optional[Callable[[], Any]]]" = queue.Queue(
            maxsize=max(1, int(queue_size))
        )
        self._error: Optional[BaseException] = None
        self._idle = threading.Event()
        self._idle.set()
        # pending counter incremented BEFORE the queue put: relying on
        # queue.unfinished_tasks alone leaves a window between idle.clear()
        # and put() where the worker, finishing the previous job, would see
        # zero unfinished tasks and re-set idle under a queued submit
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._closed = False
        self._io_retries = max(1, int(io_retries))
        self._io_retry_base_s = float(io_retry_base_s)
        self._watchdog: Optional[Any] = None
        if hang_warn_s and hang_warn_s > 0:
            from sheeprl_tpu.resilience.retry import Watchdog

            self._watchdog = Watchdog(
                float(hang_warn_s),
                on_stall=lambda stalled: warnings.warn(
                    f"checkpoint writer job has made no progress for "
                    f"{stalled:.0f}s — storage may be wedged",
                    RuntimeWarning,
                ),
                name="ckpt-writer-watchdog",
            )
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _run_job(self, job: Callable[[], Any]) -> Any:
        """One job, with jittered-backoff retry on transient IO errors —
        a blip must not park an exception and void the snapshot."""
        return run_with_io_retry(job, self._io_retries, self._io_retry_base_s)

    def _loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            t0 = time.perf_counter()
            if self._watchdog is not None:
                self._watchdog.arm()
            try:
                # writer-thread span: snapshot cost shows up in the phase
                # breakdown as concurrent ckpt.snapshot time, distinct from
                # the learner's critical path (telemetry/spans.py)
                from sheeprl_tpu.telemetry.spans import span

                with span("ckpt.snapshot"):
                    nbytes = self._run_job(job)
                CHECKPOINT_MONITOR.record_save(
                    seconds=time.perf_counter() - t0,
                    nbytes=int(nbytes or 0),
                    asynchronous=True,
                )
            except BaseException as e:  # parked, re-raised on next submit/flush
                self._error = e
                CHECKPOINT_MONITOR.record_error()
            finally:
                if self._watchdog is not None:
                    self._watchdog.disarm()
                self._queue.task_done()
                with self._pending_lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    # -- API -----------------------------------------------------------------
    @property
    def in_flight(self) -> int:
        return self._queue.unfinished_tasks

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    def submit(self, job: Callable[[], Any]) -> None:
        """Enqueue a save job (a callable returning the bytes written).
        Blocks when the bounded queue is full (back-pressure)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        self._raise_pending()
        with self._pending_lock:
            self._pending += 1
            self._idle.clear()
        self._queue.put(job)
        CHECKPOINT_MONITOR.record_depth(self.in_flight)

    def flush(self, timeout_s: Optional[float] = None) -> bool:
        """Wait until every queued job has finished.  Raises a parked writer
        error; returns False only on timeout."""
        done = self._idle.wait(timeout_s)
        self._raise_pending()
        return done

    def close(self, timeout_s: Optional[float] = 300.0) -> None:
        """Drain outstanding jobs and stop the thread (idempotent).  Must
        return within ~``timeout_s`` even when the worker is wedged on a
        dead disk: every wait below is bounded, the sentinel put uses a
        timeout too (a full bounded queue under a stuck worker would
        otherwise block forever), and an un-joinable worker is abandoned
        with a warning — it is a daemon thread, so interpreter shutdown
        does not hang on it."""
        if self._closed:
            return
        self._closed = True
        drained = self._idle.wait(timeout_s)
        # the wedged path's residual waits scale DOWN with a small timeout_s
        # (close(0.3) must not spend a fixed 5+5s on sentinel + join)
        grace = 5.0 if timeout_s is None else max(0.1, min(5.0, float(timeout_s)))
        try:
            self._queue.put(None, timeout=grace)
        except queue.Full:
            pass  # wedged worker + full queue: the join below gives up fast
        self._thread.join(timeout_s if drained else grace)
        if self._thread.is_alive():
            abandoned = max(self.in_flight, 1)
            try:
                warnings.warn(
                    f"checkpoint writer did not drain within "
                    f"{timeout_s if drained else grace}s; abandoning the daemon "
                    f"thread with ~{abandoned} job(s) wedged (likely dead "
                    "storage) — those snapshots stay uncommitted and are "
                    "invisible to resume",
                    RuntimeWarning,
                )
            except Exception:
                pass  # warning machinery can be torn down at interpreter exit
        if self._watchdog is not None:
            self._watchdog.close()
        self._raise_pending()
