"""Preemption (SIGTERM/SIGINT) handling for long training runs.

TPU fleets preempt: maintenance events and spot reclaims deliver SIGTERM
with a grace window.  The guard converts the first signal into a FLAG the
train loops poll once per iteration — at the next checkpoint opportunity
they run a final SYNCHRONOUS committed save and exit cleanly, instead of
dying mid-write.  A second signal restores the original disposition and
re-raises it, so a stuck save can still be killed.

Installed by ``parallel.fabric.build_fabric`` (main thread only — CPython
restricts ``signal.signal`` to it; worker threads and tests that build
fabrics off-thread simply skip installation).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Any, Dict, Optional


class PreemptionGuard:
    """Process-wide latch flipped by SIGTERM/SIGINT."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._signum: Optional[int] = None
        self._previous: Dict[int, Any] = {}
        self._installed = False

    # -- installation --------------------------------------------------------
    def install(self) -> bool:
        """Install handlers for SIGTERM and SIGINT.  Returns False when not
        possible (non-main thread) — the run then simply has no graceful
        preemption, same as before this subsystem."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        try:
            for signum in (signal.SIGTERM, signal.SIGINT):
                self._previous[signum] = signal.signal(signum, self._handle)
        except (ValueError, OSError):
            self._restore()
            return False
        self._installed = True
        return True

    def _restore(self) -> None:
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._previous.clear()
        self._installed = False

    def _handle(self, signum: int, frame: Any) -> None:
        if self._event.is_set():
            # second signal: the graceful path is stuck — restore defaults
            # and re-deliver so the process actually dies
            self._restore()
            os.kill(os.getpid(), signum)
            return
        self._signum = signum
        self._event.set()

    # -- queries -------------------------------------------------------------
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def signal_name(self) -> Optional[str]:
        if self._signum is None:
            return None
        try:
            return signal.Signals(self._signum).name
        except ValueError:
            return str(self._signum)

    def clear_latch(self) -> None:
        """Clear a latched signal WITHOUT uninstalling handlers.  Called at
        the start of every ``cli.run``: a preemption latched during a
        previous run in the same interpreter (exploration→finetuning
        chains, notebooks) was already honored by that run's final save —
        the next run must start un-preempted, not exit after one update."""
        self._event.clear()
        self._signum = None

    def reset(self) -> None:
        """Clear the latch and uninstall (tests / sequential runs)."""
        self.clear_latch()
        self._restore()


#: The process-global guard; fabrics install it, train loops poll it.
PREEMPTION_GUARD = PreemptionGuard()


def install_preemption_handler() -> bool:
    return PREEMPTION_GUARD.install()


def preemption_requested() -> bool:
    return PREEMPTION_GUARD.requested()
