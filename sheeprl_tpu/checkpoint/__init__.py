"""Fault-tolerant checkpointing subsystem (see docs/checkpointing.md).

Async snapshots (``writer``), a durable multi-rank commit protocol
(``protocol``), preemption handling (``preemption``), retention and resume
discovery (``manager``), on shared host-serialization primitives
(``serialize``).  The legacy ``utils.checkpoint`` module re-exports from
here for backwards compatibility.
"""

from sheeprl_tpu.checkpoint.manager import CheckpointManager, resolve_auto_resume
from sheeprl_tpu.checkpoint.rollback import rollback_state
from sheeprl_tpu.checkpoint.preemption import (
    PREEMPTION_GUARD,
    PreemptionGuard,
    install_preemption_handler,
    preemption_requested,
)
from sheeprl_tpu.checkpoint.protocol import (
    checkpoint_step,
    gc_checkpoints,
    is_committed,
    latest_checkpoint,
    list_checkpoints,
    newer_checkpoint,
    quarantine_checkpoint,
    read_manifest,
    verify_checkpoint,
    verify_or_quarantine,
    wait_for_commit,
)
from sheeprl_tpu.checkpoint.serialize import (
    KeyArrayRef,
    durable_write,
    from_host_tree,
    load_checkpoint,
    save_checkpoint,
    snapshot_tree,
    to_host_tree,
)
from sheeprl_tpu.checkpoint.writer import AsyncCheckpointWriter

__all__ = [
    "AsyncCheckpointWriter",
    "CheckpointManager",
    "KeyArrayRef",
    "PREEMPTION_GUARD",
    "PreemptionGuard",
    "checkpoint_step",
    "durable_write",
    "from_host_tree",
    "gc_checkpoints",
    "install_preemption_handler",
    "is_committed",
    "latest_checkpoint",
    "list_checkpoints",
    "load_checkpoint",
    "newer_checkpoint",
    "preemption_requested",
    "quarantine_checkpoint",
    "read_manifest",
    "resolve_auto_resume",
    "save_checkpoint",
    "snapshot_tree",
    "to_host_tree",
    "verify_checkpoint",
    "verify_or_quarantine",
    "wait_for_commit",
]
