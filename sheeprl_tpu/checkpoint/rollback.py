"""Rollback-to-last-committed-checkpoint (the health sentinels' restore path).

The divergence detector (``resilience/health.py``) decides a run's params
are garbage; this module answers "what do we restore?": the newest
COMMITTED snapshot of the *current* run, CRC-verified before it is
trusted (a rollback onto a bit-rotted snapshot would trade one kind of
garbage for another).  Damaged snapshots are quarantined exactly like the
resume path does and the next newest commit is tried.

Only the current run's own checkpoint root is searched — a rollback must
never silently jump to a *different* run's weights; when the run has no
committed snapshot yet the caller surfaces :class:`~sheeprl_tpu.
resilience.health.DivergenceError` instead, and the supervisor's
restart-with-``resume_from=auto`` becomes the (cross-run) rollback.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def rollback_state(ckpt_mgr: Any, fabric: Any) -> Tuple[Optional[dict], Optional[Any]]:
    """``(state, step_dir)`` of the newest intact committed snapshot of
    this run, or ``(None, None)`` when none exists.

    Drains the async writer first so a commit already snapshotted (the
    usual case — the divergence window postdates the last cadence save)
    is eligible rather than silently skipped mid-flight.
    """
    from sheeprl_tpu.checkpoint.protocol import verify_or_quarantine

    ckpt_mgr.flush()
    target = ckpt_mgr.latest()
    while target is not None:
        problems = verify_or_quarantine(target)
        if not problems:
            break
        # quarantine renamed it step_*.corrupt (or failed on a read-only
        # store — latest() would then return it again, so bail to None
        # rather than spin); either way look again
        nxt = ckpt_mgr.latest()
        target = None if nxt == target else nxt
    if target is None:
        return None, None
    return fabric.load(target), target
