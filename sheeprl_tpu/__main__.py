"""``python -m sheeprl_tpu`` — training CLI.

Subcommand-style flags mirror the reference's extra entry points
(reference: pyproject.toml:57-61): ``--eval``, ``--register-model``,
``--agents``.
"""

import sys

from sheeprl_tpu.cli import available_agents, evaluation, registration, run

if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "--eval":
        evaluation(argv[1:])
    elif argv and argv[0] == "--register-model":
        registration(argv[1:])
    elif argv and argv[0] == "--agents":
        available_agents()
    else:
        run(argv)
