"""``python -m sheeprl_tpu`` — training CLI.

Subcommand-style flags mirror the reference's extra entry points
(reference: pyproject.toml:57-61): ``eval``/``--eval``,
``register-model``/``--register-model``, ``agents``/``--agents``.
"""

import sys

from sheeprl_tpu.cli import available_agents, evaluation, registration, run, serve

if __name__ == "__main__":
    argv = sys.argv[1:]
    cmd = argv[0].lstrip("-") if argv else ""
    if cmd == "eval":
        evaluation(argv[1:])
    elif cmd == "serve":
        serve(argv[1:])
    elif cmd == "register-model":
        registration(argv[1:])
    elif cmd == "agents":
        available_agents()
    else:
        run(argv)
