"""Read-only live introspection endpoint for *training* runs.

The serving layer has had ``/healthz`` + ``/v1/stats`` since PR 6; a
training run had nothing — a wedged learner on a v5e could only be
diagnosed by attaching a debugger.  This module reuses the serve
``server.py`` pattern (stdlib ``ThreadingHTTPServer`` + JSON, no
third-party web framework — the container bakes no extra deps and
every handler is a dict read) to expose the telemetry subsystem:

* ``GET /healthz``     — liveness: pid, uptime, run dir, hub sources
* ``GET /metrics``     — every hub metric in Prometheus text exposition
  format (``text/plain; version=0.0.4``), ready for a scrape config
* ``GET /v1/phase``    — the span tracker's current phase breakdown
* ``GET /v1/recorder`` — the flight recorder's newest events (``?n=``)

Armed per run via ``telemetry.introspect.port`` (``0`` binds an
ephemeral port; the chosen URL is printed at startup for harnesses to
parse).  Strictly read-only: no endpoint mutates run state, so exposing
it on localhost during a multi-day capture run is safe.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

#: The Prometheus text exposition content type (version is part of the
#: scrape contract — tests golden it).
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def prometheus_name(key: str) -> str:
    """``Compile/executables`` → ``sheeprl_compile_executables``."""
    name = _NAME_RE.sub("_", key.strip()).lower().strip("_")
    return f"sheeprl_{name}"


def prometheus_text(metrics: Dict[str, float]) -> str:
    """Render a metric dict in the Prometheus text exposition format.

    Every hub metric is a gauge (the counters are cumulative values read
    at scrape time, which Prometheus models fine as gauges; claiming
    ``counter`` would require never-reset semantics the monitors don't
    promise).  Keys sort for a stable, diffable exposition."""
    lines = []
    seen = set()
    for key in sorted(metrics):
        name = prometheus_name(key)
        if name in seen:  # two keys collapsing to one name: first wins
            continue
        seen.add(name)
        try:
            value = float(metrics[key])
        except (TypeError, ValueError):
            continue
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


class IntrospectionServer:
    """HTTP wrapper over the hub/spans/recorder globals.

    ``port=0`` binds an ephemeral port; :attr:`url` is resolved after
    construction.  The server thread is a daemon — it must never keep a
    finished training process alive."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, stall_after_s: float = 0.0):
        self._started_at = time.time()
        #: /healthz reports ``stalled: true`` (HTTP 503) when the newest
        #: completed update dispatch is older than this (0 = detection off).
        #: Set from ``telemetry.stall_after_s`` by ``telemetry.setup_run``.
        self.stall_after_s = float(stall_after_s or 0.0)
        self._httpd = ThreadingHTTPServer((host, int(port)), _make_handler(self))
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    @property
    def uptime_s(self) -> float:
        return time.time() - self._started_at

    def start(self) -> "IntrospectionServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="sheeprl-introspect", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self) -> "IntrospectionServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def _make_handler(server: IntrospectionServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args: Any) -> None:  # quiet
            pass

        def _reply_bytes(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, payload: Dict[str, Any]) -> None:
            self._reply_bytes(
                code, json.dumps(payload, default=str).encode(), "application/json"
            )

        def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
            from sheeprl_tpu.telemetry.hub import HUB
            from sheeprl_tpu.telemetry.recorder import RECORDER
            from sheeprl_tpu.telemetry.spans import SPANS
            from sheeprl_tpu.telemetry.tracer import TRACER

            try:
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/") or "/"
                if path == "/healthz":
                    # liveness detail (ISSUE 14): how stale is the training
                    # loop?  `last_update_age_s` is seconds since the newest
                    # COMPLETED update dispatch (null before the first one —
                    # warm-up compiles are not a stall); past
                    # telemetry.stall_after_s the probe flips `stalled` and
                    # answers 503, so the supervisor and k8s-style external
                    # probes can tell hung from healthy without killing blind.
                    age = SPANS.last_update_age_s()
                    stalled = bool(
                        server.stall_after_s > 0
                        and age is not None
                        and age > server.stall_after_s
                    )
                    self._reply_json(
                        503 if stalled else 200,
                        {
                            "ok": not stalled,
                            "stalled": stalled,
                            "last_update_age_s": None if age is None else round(age, 3),
                            "updates_done": SPANS.updates_done,
                            "stall_after_s": server.stall_after_s,
                            "pid": os.getpid(),
                            "uptime_s": round(server.uptime_s, 3),
                            "run_dir": RECORDER.run_dir,
                            "last_step": HUB.last_step,
                            "sources": HUB.source_names(),
                            "trace_active": TRACER.active,
                            "recorder_events": len(RECORDER),
                        },
                    )
                elif path == "/metrics":
                    metrics = dict(HUB.collect())
                    metrics["Telemetry/uptime_s"] = round(server.uptime_s, 3)
                    metrics["Telemetry/recorder_events"] = float(len(RECORDER))
                    metrics["Telemetry/last_step"] = float(HUB.last_step)
                    self._reply_bytes(
                        200, prometheus_text(metrics).encode(), PROMETHEUS_CONTENT_TYPE
                    )
                elif path == "/v1/phase":
                    self._reply_json(200, SPANS.breakdown())
                elif path == "/v1/recorder":
                    qs = parse_qs(parsed.query)
                    n = None
                    if "n" in qs:
                        try:
                            n = max(1, int(qs["n"][0]))
                        except ValueError:
                            n = None
                    self._reply_json(
                        200,
                        {
                            "events": RECORDER.snapshot(n),
                            "total": len(RECORDER),
                            "last_dump": RECORDER.last_dump,
                        },
                    )
                else:
                    self._reply_json(404, {"error": f"unknown path {self.path}"})
            except BrokenPipeError:
                pass
            except Exception as e:
                try:
                    self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                except Exception:
                    pass

    return Handler
