"""On-demand XLA profiler capture windows.

``utils.profiler.ProfilerGate`` (PR 1) can only arm a trace from the
config *before the run starts*; the pending v5e captures (ROADMAP items
1/2/4/5) need traces of a *live* run at an update the operator picks when
the steady state looks wrong.  :class:`TraceScheduler` arms programmatic
``jax.profiler`` windows three ways:

* ``telemetry.trace_at=[120,4000]`` — update numbers from the config;
* ``SHEEPRL_TRACE_AT=120,4000``      — same list via the environment (the
  spelling that reaches an already-launched job's restart);
* ``SIGUSR1``                        — arm ONE window at the next update of
  a live process (``kill -USR1 <pid>``), no restart at all.

Update numbering is the train-dispatch count: the span layer calls
:meth:`tick` whenever a top-level ``update.dispatch`` span opens (the
``Time/train_time`` phase every loop already wraps), so no per-loop wiring
exists.  Each window captures ``telemetry.trace_updates`` dispatches into
``<log_dir>/trace/update_<n>`` (viewable with TensorBoard's profile
plugin / xprof).  While a window is open the span layer fences device
dispatch boundaries, so the trace's host markers line up with device
streams; when no window is armed the fence — and its cost — is absent.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Any, Callable, List, Optional

ENV_VAR = "SHEEPRL_TRACE_AT"


def _default_start(path: str) -> None:
    import jax

    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)


def _default_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class TraceScheduler:
    """Arms/stops profiler trace windows on the update-tick stream."""

    def __init__(
        self,
        start_fn: Optional[Callable[[str], None]] = None,
        stop_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        self._lock = threading.Lock()
        self._start_fn = start_fn or _default_start
        self._stop_fn = stop_fn or _default_stop
        self._at: frozenset = frozenset()
        self._window = 2
        self._dir: Optional[str] = None
        self._count = 0
        self._stop_at = 0
        self._signal_armed = False
        self._signal_installed = False
        #: a window is open right now — the span layer reads this to decide
        #: whether span edges fence the device
        self.active = False
        self.windows_captured = 0

    # -- configuration -------------------------------------------------------
    def configure(self, tcfg: Any = None, log_dir: Optional[str] = None) -> None:
        """Apply the ``telemetry.*`` trace knobs for a new run.  Resets the
        update counter (update numbers are per run); an open window from a
        previous run in this interpreter is closed first."""
        tcfg = tcfg or {}
        self.close()
        env_at: List[int] = []
        raw = os.environ.get(ENV_VAR, "").strip()
        if raw:
            try:
                env_at = [int(tok) for tok in raw.replace(",", " ").split()]
            except ValueError:
                import warnings

                warnings.warn(f"ignoring malformed {ENV_VAR}={raw!r}", RuntimeWarning)
        cfg_at = [int(v) for v in (tcfg.get("trace_at") or [])]
        with self._lock:
            self._at = frozenset(cfg_at + env_at)
            self._window = max(1, int(tcfg.get("trace_updates", 2)))
            self._dir = tcfg.get("trace_dir") or (
                os.path.join(log_dir, "trace") if log_dir else None
            )
            self._count = 0
            self._signal_armed = False

    def install_signal(self) -> bool:
        """SIGUSR1 → arm one window at the next update.  Main thread only
        (CPython restricts ``signal.signal``); elsewhere it is a no-op —
        same contract as the preemption guard."""
        if self._signal_installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        if not hasattr(signal, "SIGUSR1"):  # pragma: no cover - non-POSIX
            return False
        try:
            signal.signal(signal.SIGUSR1, self._handle_signal)
        except (ValueError, OSError):
            return False
        self._signal_installed = True
        return True

    def _handle_signal(self, signum: int, frame: Any) -> None:
        self.request()

    def request(self) -> None:
        """Arm one trace window at the next tick (the SIGUSR1 path, also
        callable directly — e.g. from an operator console)."""
        with self._lock:
            self._signal_armed = True

    # -- the tick stream -----------------------------------------------------
    def tick(self) -> None:
        """One train dispatch is about to run.  Called by the span layer on
        every top-level ``update.dispatch`` span open; cheap when nothing is
        armed (one lock, two int tests)."""
        with self._lock:
            self._count += 1
            n = self._count
            fire_stop = self.active and n >= self._stop_at
            fire_start = (not self.active and not fire_stop) and (
                n in self._at or self._signal_armed
            )
            if fire_start:
                self._signal_armed = False
        if fire_stop:
            self._stop(n)
            with self._lock:  # a stop tick can also be an armed start tick
                fire_start = n in self._at or self._signal_armed
                if fire_start:
                    self._signal_armed = False
        if fire_start:
            self._start(n)

    @property
    def update_count(self) -> int:
        with self._lock:
            return self._count

    # -- window edges --------------------------------------------------------
    def _start(self, n: int) -> None:
        path = os.path.join(self._dir or os.getcwd(), f"update_{n:06d}")
        try:
            self._start_fn(path)
        except Exception as e:  # tracing must never take down training
            from sheeprl_tpu.telemetry.recorder import RECORDER

            RECORDER.record("trace.error", update=n, error=f"{type(e).__name__}: {e}")
            return
        with self._lock:
            self.active = True
            self._stop_at = n + self._window
        from sheeprl_tpu.telemetry.recorder import RECORDER

        RECORDER.record("trace.start", update=n, path=path, updates=self._window)

    def _stop(self, n: Optional[int] = None) -> None:
        try:
            self._stop_fn()
        except Exception:
            pass
        with self._lock:
            self.active = False
            self.windows_captured += 1
        from sheeprl_tpu.telemetry.recorder import RECORDER

        RECORDER.record("trace.stop", update=n if n is not None else self._count)

    def close(self) -> None:
        """Stop an open window (end of run / reconfigure)."""
        if self.active:
            self._stop()


#: The process-global trace scheduler the span layer ticks.
TRACER = TraceScheduler()
