"""Step-phase spans: nestable host-side timing with per-window breakdowns.

The named timers (``utils/timer.py``) answer "how long did phase X take";
they cannot answer "what FRACTION of the window went where" — the number
that decides whether to tune ``traj_queue_slots`` (queue waits dominate)
or shard the model further (update dispatch dominates).  The span tracker
keeps a per-thread stack of open spans, attributes each span its
EXCLUSIVE time (children subtracted), and aggregates a rolling window
into phase-breakdown fractions that sum to ~1.0 (an ``other`` bucket
absorbs untracked host time).

Span taxonomy (docs/telemetry.md):

* ``rollout``          — env interaction / segment collection
* ``queue.wait``       — the learner blocked on the trajectory queue
* ``replay.write``     — host→ring staging of new rows
* ``update.dispatch``  — the train-phase device dispatch (fused on-device
  sampling included — it is part of the same executable)
* ``param.broadcast``  — learner→actor param publication
* ``ckpt.snapshot``    — checkpoint serialize+write (writer thread)
* ``pipeline.stage.<name>.fwd`` / ``.bwd`` — per-stage forward/backward
  wall time of the pipelined world-model update, measured by
  ``bench.py --mode pipeline``'s standalone stage programs
  (``parallel/pipeline.py compile_stage_pair``); inside the fused train
  phase the stages appear as ``pipeline.<name>`` ``named_scope``s in
  device traces instead (one dispatch = one ``update.dispatch`` span).
  The derived first-class metric is ``Pipeline/bubble_frac`` — the
  schedule's idle fraction ``(S-1)/(M+S-1)`` (docs/pipeline.md).

Wiring is centralized: ``utils.timer`` bridges the two phase timers every
loop already has (:data:`TIMER_PHASES`), and the sebulba runner /
topology / checkpoint / replay layers open their own spans — no per-loop
copies.  Opening a top-level ``update.dispatch`` span also ticks the
trace scheduler (``tracer.py``), which is how trace windows count
updates without the loops knowing.

Device attribution: dispatch is asynchronous, so a span's host time is
not its device time.  While a trace window is armed (``TRACER.active``)
or ``telemetry.spans.sync`` is set, span edges drain the device
(``utils.device_sync`` — ``block_until_ready`` resolves at dispatch on
the axon tunnel, see BENCH_TPU.md), making phases attributable exactly
when someone is looking; steady-state runs never pay the fence.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from sheeprl_tpu.telemetry.hub import HUB
from sheeprl_tpu.telemetry.recorder import RECORDER
from sheeprl_tpu.telemetry.tracer import TRACER

#: timer-name → span-phase bridge (utils/timer.py opens these automatically,
#: which is what wires all 12 algo loops without touching them)
TIMER_PHASES: Dict[str, str] = {
    "Time/env_interaction_time": "rollout",
    "Time/train_time": "update.dispatch",
}

_now = time.perf_counter


class _Span:
    __slots__ = ("name", "start", "child_s")

    def __init__(self, name: str, start: float) -> None:
        self.name = name
        self.start = start
        self.child_s = 0.0


class SpanTracker:
    """Process-global span stack (per-thread) + windowed phase aggregator."""

    def __init__(self) -> None:
        self.enabled = True
        self.sync = False
        self._lock = threading.Lock()
        self._local = threading.local()
        self._excl: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._window_start = _now()
        # liveness signal for /healthz (introspect.py): wall time of the
        # newest COMPLETED top-level update.dispatch span + total count —
        # survives window rolls, so a stalled learner is visible however
        # long it has been wedged
        self._last_update_done: Optional[float] = None
        self._updates_done = 0

    # -- configuration -------------------------------------------------------
    def configure(self, cfg: Any = None) -> None:
        """Apply the ``telemetry.spans`` config group."""
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enabled", True))
        self.sync = bool(cfg.get("sync", False))

    # -- the span stack ------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @staticmethod
    def _fence() -> None:
        try:
            from sheeprl_tpu.utils.utils import device_sync

            device_sync()
        except Exception:
            pass  # attribution is best-effort; never take down the run

    def push(self, name: str) -> Optional[_Span]:
        """Open a span; returns the token :meth:`pop` needs (None when
        disabled — pop of None is a no-op, so call sites stay branch-free)."""
        if not self.enabled:
            return None
        stack = self._stack()
        if name == "update.dispatch" and not stack:
            # the update tick stream the trace scheduler counts on
            TRACER.tick()
        if self.sync or TRACER.active:
            self._fence()
        span = _Span(name, _now())
        stack.append(span)
        return span

    def pop(self, token: Optional[_Span]) -> None:
        """Close ``token`` (and any span opened under it that leaked — a
        raise between push and pop unwinds with the parent)."""
        if token is None:
            return
        if self.sync or TRACER.active:
            self._fence()
        stack = self._stack()
        end = _now()
        while stack:
            span = stack.pop()
            dur = max(0.0, end - span.start)
            excl = max(0.0, dur - span.child_s)
            if stack:
                stack[-1].child_s += dur
            with self._lock:
                self._excl[span.name] = self._excl.get(span.name, 0.0) + excl
                self._counts[span.name] = self._counts.get(span.name, 0) + 1
            if not stack:
                # top-level span edges are flight-recorder events (bounded
                # ring — per-update cadence, not per-env-step)
                RECORDER.record("span", name=span.name, seconds=round(dur, 6))
                if span.name == "update.dispatch":
                    with self._lock:
                        self._last_update_done = time.time()
                        self._updates_done += 1
            if span is token:
                return

    @contextmanager
    def span(self, name: str):
        token = self.push(name)
        try:
            yield token
        finally:
            self.pop(token)

    def depth(self) -> int:
        return len(self._stack())

    # -- liveness ------------------------------------------------------------
    def last_update_age_s(self) -> Optional[float]:
        """Seconds since the newest completed update dispatch (None before
        the first one — warm-up compiles can legitimately take many
        minutes, so pre-first-update runs are never called stalled)."""
        with self._lock:
            if self._last_update_done is None:
                return None
            return max(0.0, time.time() - self._last_update_done)

    @property
    def updates_done(self) -> int:
        with self._lock:
            return self._updates_done

    # -- window aggregation --------------------------------------------------
    def breakdown(self) -> Dict[str, Any]:
        """The current window's phase breakdown.

        Fractions are normalized against ``max(window wall, Σ exclusive)``:
        spans on concurrent threads (the checkpoint writer overlapping the
        learner) can legitimately sum past wall time, and the breakdown
        must still sum to ~1.0.  ``other_frac`` is the untracked remainder
        of the window wall."""
        with self._lock:
            excl = dict(self._excl)
            counts = dict(self._counts)
            window_s = max(_now() - self._window_start, 1e-9)
        tracked = sum(excl.values())
        total = max(window_s, tracked)
        phases = {
            name: {
                "seconds": round(s, 6),
                "frac": round(s / total, 6),
                "count": counts.get(name, 0),
            }
            for name, s in sorted(excl.items())
        }
        return {
            "window_s": round(window_s, 6),
            "phases": phases,
            "other_frac": round(max(0.0, window_s - tracked) / total, 6),
        }

    def metrics(self) -> Dict[str, float]:
        """``Phase/*`` fractions for the hub flush (empty when no span
        closed this window — a run with spans disabled emits nothing)."""
        bd = self.breakdown()
        if not bd["phases"]:
            return {}
        out = {f"Phase/{name}": p["frac"] for name, p in bd["phases"].items()}
        out["Phase/other"] = bd["other_frac"]
        return out

    def roll_window(self) -> None:
        """Start a fresh aggregation window (fired by the per-interval
        metric flush via the hub's ``on_roll`` hook)."""
        with self._lock:
            self._excl.clear()
            self._counts.clear()
            self._window_start = _now()

    def reset(self) -> None:
        """Tests: fresh window + default knobs (per-thread stacks drain
        naturally as their context managers exit)."""
        self.roll_window()
        self.enabled = True
        self.sync = False
        with self._lock:
            self._last_update_done = None
            self._updates_done = 0


#: The process-global span tracker.
SPANS = SpanTracker()

#: Module-level convenience: ``with span("queue.wait"): ...``
span = SPANS.span

HUB.register("spans", SPANS.metrics, on_roll=SPANS.roll_window)
