"""The telemetry hub: ONE registration API and ONE flush contract.

Before this subsystem, run telemetry was scattered across three ad-hoc
process-global monitors (``COMPILE_MONITOR`` / ``CHECKPOINT_MONITOR`` /
``RESILIENCE_MONITOR``), Sebulba's private stats sink and one-off bench
counters — each with its own read path, none of them reachable from an
exception exit.  :class:`TelemetryHub` absorbs them all behind a single
contract:

* a **source** is anything that can answer "your metrics, now" — a
  callable returning ``{name: float}`` or an object with a ``metrics()``
  method.  Sources register once (the monitors at import, Sebulba/serve
  at run start) and are polled by every flush; a source that raises is
  skipped, never fatal.
* :meth:`flush` merges every source's metrics into one dict.  It is
  non-destructive by default so the introspection endpoint can scrape
  freely; ``roll=True`` (used by the per-window metric flush) also fires
  each source's ``on_roll`` hook — e.g. the span tracker resetting its
  phase-breakdown window.
* the hub remembers the run's **logger** (attached by
  ``utils.logger.get_logger``) and the last policy step it flushed at, so
  :meth:`final_flush` — called from the ``finally`` path of ``cli.run`` —
  can land the last window of ``Compile/*`` / ``Resilience/*`` / ``Phase/*``
  counters even when the loop died mid-window (the metrics-lost-on-crash
  bug this subsystem fixes).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


class TelemetryHub:
    """Process-global metric-source registry + merged flush."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[str, Tuple[Callable[[], Dict[str, float]], Optional[Callable[[], None]]]] = {}
        self._logger: Any = None
        self.last_step: int = 0
        self._namespace: Optional[str] = None

    # -- namespacing (multi-process runs) ------------------------------------
    def set_namespace(self, prefix: Optional[str]) -> None:
        """Prefix every flushed metric with ``<prefix>/`` — pod actor cells
        set their rank (``rank2``) so their scrapes and the control-plane
        snapshots they ship to the learner's rank-0 aggregation stay
        distinguishable from the learner's own counters.  ``None`` clears."""
        with self._lock:
            self._namespace = str(prefix) if prefix else None

    # -- registration --------------------------------------------------------
    def register(
        self,
        name: str,
        source: Any,
        on_roll: Optional[Callable[[], None]] = None,
    ) -> None:
        """Register a metric source under ``name`` (replacing any previous
        holder of the name — re-registration is how a new run's Sebulba
        queues supersede the finished run's)."""
        fn = source if callable(source) else getattr(source, "metrics")
        with self._lock:
            self._sources[name] = (fn, on_roll)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def source_names(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    # -- flushing ------------------------------------------------------------
    def flush(self, roll: bool = False) -> Dict[str, float]:
        """Merge every source's metrics.  A broken source is skipped — one
        bad exporter must never take down the metric stream (or a scrape).
        ``roll=True`` additionally fires the per-source window-roll hooks
        AFTER collection, so rolling flushes see the full window."""
        with self._lock:
            items = list(self._sources.items())
            namespace = self._namespace
        out: Dict[str, float] = {}
        for _, (fn, _on_roll) in items:
            try:
                out.update(fn() or {})
            except Exception:
                continue
        if namespace:
            out = {f"{namespace}/{k}": v for k, v in out.items()}
        if roll:
            for _, (_fn, on_roll) in items:
                if on_roll is not None:
                    try:
                        on_roll()
                    except Exception:
                        continue
        return out

    def collect(self) -> Dict[str, float]:
        """Non-destructive scrape (the ``/metrics`` endpoint's read)."""
        return self.flush(roll=False)

    # -- logger plumbing (the crash-flush path) ------------------------------
    def attach_logger(self, logger: Any) -> None:
        """Remember the run's logger so :meth:`final_flush` has somewhere to
        land the last window.  Called by ``utils.logger.get_logger``."""
        if logger is not None:
            with self._lock:
                self._logger = logger

    def note_step(self, step: int) -> None:
        """Track the newest policy step flushed (``metric.flush_metrics``
        calls this) — the step :meth:`final_flush` stamps its metrics at."""
        with self._lock:
            self.last_step = max(self.last_step, int(step))

    def final_flush(self) -> Dict[str, float]:
        """Land whatever the sources still hold through the attached logger.

        Runs on the ``finally`` path of ``cli.run``: a loop that exited via
        an exception or the preemption latch never reached its next metric
        interval, so the monitors' buffered counters (the final ``Compile/*``
        executable count, the ``Resilience/*`` evidence of the fault that
        killed it) would otherwise be silently lost.  Best-effort by
        design — the logger may already be closed; telemetry must never
        mask the original exception."""
        with self._lock:
            logger, self._logger = self._logger, None
            step = self.last_step
        metrics = self.flush(roll=True)
        if logger is not None and metrics:
            try:
                logger.log_metrics(metrics, step)
            except Exception:
                pass
        return metrics

    def reset(self) -> None:
        """Detach the logger and forget the step (tests / sequential runs).
        Registered sources stay — they are process-global monitors."""
        with self._lock:
            self._logger = None
            self.last_step = 0


#: The process-global hub every monitor registers into and every flush reads.
HUB = TelemetryHub()
