"""The process-global subsystem monitors, owned by the telemetry hub.

These classes grew up in ``utils/profiler.py`` as three independent
ad-hoc globals; the telemetry subsystem absorbs them behind the hub's one
registration API and one flush contract.  ``utils.profiler`` still
re-exports ``COMPILE_MONITOR`` / ``CHECKPOINT_MONITOR`` /
``RESILIENCE_MONITOR`` as thin shims (they are the SAME objects), so
every existing call site and test keeps working unchanged.

New here vs the profiler era: notable state transitions (injected faults,
watchdog stalls, env restarts, breaker opens, quarantines, checkpoint
saves, compiles) also land in the flight recorder, so a postmortem can
reconstruct the last minutes of a dead run from one file.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.telemetry.hub import HUB
from sheeprl_tpu.telemetry.recorder import RECORDER


class RecompileLimitExceeded(RuntimeError):
    """A compile-once function exceeded its allowed recompile budget."""


class CompileMonitor:
    """Process-global per-function compile counter + abstract-signature log.

    ``count(name)`` is the number of executables built for ``name`` — the
    first compile is expected; every further one is a *recompile* caused by
    a new abstract signature.  The ``max_recompiles`` budget itself is
    enforced per-``AOTFunction`` instance (see ``parallel/compile.py``),
    which raises :class:`RecompileLimitExceeded`; this monitor is the
    process-wide aggregate view (metrics, dryrun stage summaries).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, Any]] = {}

    # -- recording (called by parallel.compile.AOTFunction) -----------------
    def begin(self, name: str, signature: Any) -> None:
        """Count one compile of ``name`` in the process-global accounting.

        Pure bookkeeping: the ``max_recompiles`` budget is enforced
        per-:class:`~sheeprl_tpu.parallel.compile.AOTFunction` *instance*
        (each instance IS one compile-once program).  The global per-name
        count would otherwise aggregate across unrelated instances that
        happen to share a name — e.g. every run constructed in the same
        test process — and trip the budget for compiles the current
        program never performed.
        """
        with self._lock:
            st = self._stats.setdefault(
                name, {"count": 0, "seconds": 0.0, "signatures": []}
            )
            st["count"] += 1
            st["signatures"].append(str(signature))

    def abort(self, name: str, signature: Any = None) -> None:
        """Roll back one ``begin`` for ``name``: the compile failed, so no
        executable exists — counters must reflect programs actually built.
        When ``signature`` is given, the MATCHING history entry (searched
        from the end) is removed rather than blindly the last one, since two
        signatures of one function can compile concurrently."""
        with self._lock:
            st = self._stats.get(name)
            if st is None or st["count"] <= 0:
                return
            st["count"] -= 1
            if not st["signatures"]:
                return
            if signature is None:
                st["signatures"].pop()
                return
            sig_str = str(signature)
            for i in range(len(st["signatures"]) - 1, -1, -1):
                if st["signatures"][i] == sig_str:
                    del st["signatures"][i]
                    break

    def end(self, name: str, seconds: float) -> None:
        with self._lock:
            st = self._stats.get(name)
            if st is not None:
                st["seconds"] += float(seconds)
        RECORDER.record("compile", name=name, seconds=round(float(seconds), 3))

    @staticmethod
    def default_limit() -> Optional[int]:
        raw = os.environ.get("SHEEPRL_MAX_RECOMPILES", "").strip()
        return int(raw) if raw else None

    # -- queries -------------------------------------------------------------
    def count(self, name: str) -> int:
        with self._lock:
            return int(self._stats.get(name, {}).get("count", 0))

    def signatures(self, name: str) -> List[str]:
        with self._lock:
            return list(self._stats.get(name, {}).get("signatures", ()))

    def totals(self) -> Tuple[int, float]:
        """(total executables compiled, total compile seconds)."""
        with self._lock:
            return (
                sum(st["count"] for st in self._stats.values()),
                sum(st["seconds"] for st in self._stats.values()),
            )

    def summary(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "count": st["count"],
                    "seconds": round(st["seconds"], 3),
                    "signatures": list(st["signatures"]),
                }
                for name, st in self._stats.items()
            }

    def delta_report(self, mark: Tuple[int, float]) -> str:
        """One human line of what compiled since ``mark`` (from totals())."""
        count, seconds = self.totals()
        return f"{count - mark[0]} executables / {seconds - mark[1]:.1f}s compile"

    def compile_metrics(self) -> Dict[str, float]:
        """Aggregate counters for the hub flush (see metric.flush_metrics)."""
        count, seconds = self.totals()
        if count == 0:
            return {}
        return {
            "Compile/executables": float(count),
            "Compile/compile_time_s": round(seconds, 3),
        }

    # hub-source alias: the hub polls ``metrics()`` on registered objects
    metrics = compile_metrics

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


#: The process-global monitor every AOTFunction reports into.
COMPILE_MONITOR = CompileMonitor()


class CheckpointMonitor:
    """Process-global accounting for the checkpointing subsystem
    (``sheeprl_tpu.checkpoint``) — the same pattern as
    :class:`CompileMonitor`: writer threads record, the telemetry hub
    surfaces the counters as ``Checkpoint/*`` without the loops threading a
    handle through."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._saves = 0
            self._async_saves = 0
            self._errors = 0
            self._bytes_total = 0
            self._seconds_total = 0.0
            self._last_seconds = 0.0
            self._last_bytes = 0
            self._max_depth = 0

    def record_save(self, seconds: float, nbytes: int, asynchronous: bool) -> None:
        with self._lock:
            self._saves += 1
            self._async_saves += 1 if asynchronous else 0
            self._bytes_total += int(nbytes)
            self._seconds_total += float(seconds)
            self._last_seconds = float(seconds)
            self._last_bytes = int(nbytes)
        RECORDER.record(
            "ckpt.save",
            seconds=round(float(seconds), 4),
            bytes=int(nbytes),
            asynchronous=bool(asynchronous),
        )

    def record_error(self) -> None:
        with self._lock:
            self._errors += 1
        RECORDER.record("ckpt.error")

    def record_depth(self, depth: int) -> None:
        with self._lock:
            self._max_depth = max(self._max_depth, int(depth))

    def metrics(self) -> Dict[str, float]:
        """``Checkpoint/save_s`` is the LAST save's wall time — for async
        saves that is writer-thread time overlapped with training, i.e. the
        cost a synchronous save would have put on the critical path."""
        with self._lock:
            if self._saves == 0:
                return {}
            return {
                "Checkpoint/save_s": round(self._last_seconds, 4),
                "Checkpoint/bytes": float(self._last_bytes),
                "Checkpoint/total_saves": float(self._saves),
                "Checkpoint/total_bytes": float(self._bytes_total),
                "Checkpoint/queue_depth_max": float(self._max_depth),
            }

    def totals(self) -> Dict[str, float]:
        with self._lock:
            return {
                "saves": self._saves,
                "async_saves": self._async_saves,
                "errors": self._errors,
                "bytes": self._bytes_total,
                "seconds": round(self._seconds_total, 4),
            }


#: The process-global monitor the checkpoint writer reports into.
CHECKPOINT_MONITOR = CheckpointMonitor()


class ResilienceMonitor:
    """Process-global accounting for the resilience subsystem
    (``sheeprl_tpu.resilience``) — retries, watchdog stalls, env restarts,
    circuit-breaker transitions, quarantined snapshots, injected faults.
    Same pattern as the other monitors: primitives record from any thread,
    the telemetry hub surfaces the counters as ``Resilience/*``.

    When nothing has been recorded, :meth:`metrics` returns ``{}`` — a run
    with fault injection disabled and no recoveries emits NO ``Resilience/*``
    metrics at all (part of the zero-overhead-when-disabled gate)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._retries = 0
            self._retry_successes = 0
            self._giveups = 0
            self._stalls = 0
            self._env_restarts = 0
            self._breaker_opens = 0
            self._quarantined = 0
            self._injected = 0
            self._injected_by_site: Dict[str, int] = {}

    def record_retry(self, site: str = "") -> None:
        with self._lock:
            self._retries += 1

    def record_retry_success(self, site: str = "") -> None:
        with self._lock:
            self._retry_successes += 1

    def record_giveup(self, site: str = "") -> None:
        with self._lock:
            self._giveups += 1
        RECORDER.record("retry.giveup", site=site)

    def record_stall(self, name: str = "") -> None:
        with self._lock:
            self._stalls += 1
        RECORDER.record("watchdog.stall", name=name)

    def record_env_restart(self, count: int = 1) -> None:
        with self._lock:
            self._env_restarts += int(count)
        RECORDER.record("env.restart", envs=int(count))

    def record_breaker(self, name: str, state: str) -> None:
        if state == "open":
            with self._lock:
                self._breaker_opens += 1
            RECORDER.record("breaker.open", name=name)

    def record_quarantine(self, path: Any = None) -> None:
        with self._lock:
            self._quarantined += 1
        RECORDER.record("ckpt.quarantine", path=str(path) if path is not None else None)

    def record_injection(self, site: str, kind: str) -> None:
        with self._lock:
            self._injected += 1
            self._injected_by_site[site] = self._injected_by_site.get(site, 0) + 1
        RECORDER.record("fault.injected", site=site, fault=kind)

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            if self._retries:
                out["Resilience/retries"] = float(self._retries)
            if self._retry_successes:
                out["Resilience/retry_successes"] = float(self._retry_successes)
            if self._giveups:
                out["Resilience/giveups"] = float(self._giveups)
            if self._stalls:
                out["Resilience/watchdog_stalls"] = float(self._stalls)
            if self._env_restarts:
                out["Resilience/env_restarts"] = float(self._env_restarts)
            if self._breaker_opens:
                out["Resilience/breaker_opens"] = float(self._breaker_opens)
            if self._quarantined:
                out["Resilience/quarantined_snapshots"] = float(self._quarantined)
            if self._injected:
                out["Resilience/faults_injected"] = float(self._injected)
            return out

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "retries": self._retries,
                "retry_successes": self._retry_successes,
                "giveups": self._giveups,
                "stalls": self._stalls,
                "env_restarts": self._env_restarts,
                "breaker_opens": self._breaker_opens,
                "quarantined": self._quarantined,
                "injected": self._injected,
                "injected_by_site": dict(self._injected_by_site),
            }


#: The process-global monitor every resilience primitive reports into.
RESILIENCE_MONITOR = ResilienceMonitor()


# absorbed behind the hub's one registration API / one flush contract
HUB.register("compile", COMPILE_MONITOR.compile_metrics)
HUB.register("checkpoint", CHECKPOINT_MONITOR.metrics)
HUB.register("resilience", RESILIENCE_MONITOR.metrics)
