"""Unified telemetry subsystem (docs/telemetry.md).

One place for everything a production RL run needs to be observable:

* :mod:`~sheeprl_tpu.telemetry.hub`       — ``HUB``: one registration API,
  one ``flush()`` contract over every metric source
* :mod:`~sheeprl_tpu.telemetry.monitors`  — the compile / checkpoint /
  resilience monitors (the old ``utils.profiler`` globals are thin shims
  over these)
* :mod:`~sheeprl_tpu.telemetry.spans`     — ``SPANS``: nestable step-phase
  spans → per-window ``Phase/*`` breakdown fractions
* :mod:`~sheeprl_tpu.telemetry.tracer`    — ``TRACER``: on-demand XLA
  profiler windows (``telemetry.trace_at`` / ``SHEEPRL_TRACE_AT`` /
  ``SIGUSR1``)
* :mod:`~sheeprl_tpu.telemetry.recorder`  — ``RECORDER``: bounded flight
  recorder → ``postmortem.json`` on crash / watchdog teardown /
  preemption / fault-drill abort
* :mod:`~sheeprl_tpu.telemetry.introspect` — read-only HTTP endpoint
  (``/healthz``, ``/metrics`` Prometheus text, ``/v1/phase``,
  ``/v1/recorder``) armed via ``telemetry.introspect.port``

``setup_run`` is the per-run entry point, called centrally from
``utils.logger.get_logger`` — no per-loop wiring.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from sheeprl_tpu.telemetry.hub import HUB, TelemetryHub  # noqa: F401
from sheeprl_tpu.telemetry.introspect import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    IntrospectionServer,
    prometheus_text,
)
from sheeprl_tpu.telemetry.monitors import (  # noqa: F401
    CHECKPOINT_MONITOR,
    COMPILE_MONITOR,
    RESILIENCE_MONITOR,
    CheckpointMonitor,
    CompileMonitor,
    RecompileLimitExceeded,
    ResilienceMonitor,
)
from sheeprl_tpu.telemetry.recorder import RECORDER, FlightRecorder  # noqa: F401
from sheeprl_tpu.telemetry.spans import SPANS, SpanTracker, span  # noqa: F401
from sheeprl_tpu.telemetry.tracer import TRACER, TraceScheduler  # noqa: F401

_SERVER: Optional[IntrospectionServer] = None
_SERVER_LOCK = threading.Lock()


def introspection_server() -> Optional[IntrospectionServer]:
    """The live run's introspection server, if one is armed."""
    return _SERVER


def setup_run(cfg: Any, log_dir: Optional[str], rank: int = 0) -> None:
    """Configure the telemetry subsystem for one run.

    Called from ``utils.logger.get_logger`` — the one construction step
    every training loop (all 12 algos, the Sebulba drivers, evaluation)
    already goes through — so spans, the tracer's trace windows, the
    flight recorder's run directory, and the introspection endpoint are
    armed without per-loop wiring.  Idempotent across repeated calls; the
    introspection server restarts only when a port is configured."""
    tcfg = (cfg.get("telemetry") or {}) if hasattr(cfg, "get") else {}
    SPANS.configure(tcfg.get("spans") or {})
    RECORDER.configure(tcfg.get("recorder") or {}, run_dir=log_dir)
    TRACER.configure(tcfg, log_dir)
    TRACER.install_signal()  # SIGUSR1 → one trace window (main thread only)

    if rank != 0:
        return
    icfg = tcfg.get("introspect") or {}
    port = icfg.get("port", None)
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
        if port is None:
            return
        _SERVER = IntrospectionServer(
            host=str(icfg.get("host", "127.0.0.1")),
            port=int(port),
            stall_after_s=float(tcfg.get("stall_after_s", 600.0) or 0.0),
        ).start()
    # flush: harnesses (run_ci stage 12) parse this line off a pipe while
    # the run itself may not print again for minutes
    print(f"telemetry introspection on {_SERVER.url}", flush=True)


def shutdown_run() -> None:
    """End-of-run teardown: stop an open trace window and the introspection
    server.  Called from the ``finally`` path of ``cli.run``."""
    TRACER.close()
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
