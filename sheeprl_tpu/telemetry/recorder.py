"""Flight recorder: a bounded in-memory ring of recent runtime events.

Production RL dataflow dies in ways the metric stream cannot explain after
the fact: a chaos drill aborts, a watchdog tears a wedged vector env down
once too often, a preemption latch fires mid-update.  The recorder keeps
the last ``capacity`` events — span edges, injected faults, watchdog
stalls, env restarts, breaker opens, compiles, checkpoint saves, queue
depth samples — and on any abnormal exit dumps them as a structured
``postmortem.json`` under the run directory, together with a snapshot of
the monitor totals and the current phase breakdown.  Every chaos path
leaves evidence.

Recording is append-to-a-deque cheap and never raises; dumping is
best-effort (an atomic tmp+rename write) and never masks the exception
that triggered it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: postmortem.json schema identifier (bump on breaking layout changes)
SCHEMA = "sheeprl.postmortem/1"


class FlightRecorder:
    """Process-global bounded event ring + postmortem dumper."""

    def __init__(self, capacity: int = 2048) -> None:
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        self._run_dir: Optional[str] = None
        self._last_dump: Optional[str] = None
        self.enabled = True

    # -- configuration -------------------------------------------------------
    def configure(self, cfg: Any = None, run_dir: Optional[str] = None) -> None:
        """Apply the ``telemetry.recorder`` config group and pin the run
        directory the postmortem lands in (called per run from
        ``telemetry.setup_run``)."""
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enabled", True))
        capacity = int(cfg.get("capacity", 2048))
        with self._lock:
            if capacity != self._events.maxlen:
                self._events = deque(self._events, maxlen=capacity)
            if run_dir:
                self._run_dir = str(run_dir)

    @property
    def run_dir(self) -> Optional[str]:
        return self._run_dir

    @property
    def last_dump(self) -> Optional[str]:
        return self._last_dump

    # -- recording -----------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        """Append one event.  Hot-path-safe: one enabled test, one dict
        build, one locked deque append; never raises."""
        if not self.enabled:
            return
        evt: Dict[str, Any] = {"t": round(time.time(), 6), "kind": str(kind)}
        evt.update(fields)
        try:
            with self._lock:
                self._events.append(evt)
        except Exception:
            pass

    def snapshot(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The newest ``n`` events (all, when ``n`` is None), oldest first."""
        with self._lock:
            events = list(self._events)
        return events[-int(n):] if n else events

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
        self._last_dump = None

    # -- postmortem ----------------------------------------------------------
    def document(self, reason: str) -> Dict[str, Any]:
        """The postmortem document (also served by ``/v1/recorder``)."""
        # lazy imports: the recorder is imported by the monitors — pulling
        # them in at module level would be a cycle
        doc: Dict[str, Any] = {
            "schema": SCHEMA,
            "reason": str(reason),
            "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "run_dir": self._run_dir,
            "events": self.snapshot(),
        }
        try:
            # the newest policy step the hub flushed at: the supervisor's
            # failure classifier keys its fatal signature on (error, step) —
            # the same crash at the same step twice is deterministic
            from sheeprl_tpu.telemetry.hub import HUB

            doc["last_step"] = int(HUB.last_step)
        except Exception:
            doc["last_step"] = None
        try:
            from sheeprl_tpu.telemetry.monitors import (
                CHECKPOINT_MONITOR,
                COMPILE_MONITOR,
                RESILIENCE_MONITOR,
            )

            n_exe, compile_s = COMPILE_MONITOR.totals()
            doc["monitors"] = {
                "compile": {"executables": n_exe, "compile_time_s": round(compile_s, 3)},
                "checkpoint": CHECKPOINT_MONITOR.totals(),
                "resilience": RESILIENCE_MONITOR.totals(),
            }
        except Exception:
            doc["monitors"] = None
        try:
            from sheeprl_tpu.telemetry.spans import SPANS

            doc["phase_breakdown"] = SPANS.breakdown()
        except Exception:
            doc["phase_breakdown"] = None
        return doc

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write ``postmortem.json`` (atomic tmp+rename) and return its path.

        Target: ``path`` when given, else ``<run_dir>/postmortem.json``.
        With neither, nothing is written (a crash before the run directory
        exists — e.g. a config error — must not litter the cwd).  Never
        raises: the dump rides exception paths."""
        try:
            if path is None:
                if not self._run_dir:
                    return None
                path = os.path.join(self._run_dir, "postmortem.json")
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.document(reason), f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._last_dump = path
            return path
        except Exception:
            return None


#: The process-global flight recorder every subsystem reports events into.
RECORDER = FlightRecorder()
