"""In-trace population training on the Anakin axis (docs/population.md)."""

from sheeprl_tpu.population.core import (
    PBTConfig,
    PopulationMonitor,
    apply_level_curriculum,
    init_population_state,
    make_population_phase,
    pbt_exploit_explore,
    tile_stack,
    write_population_summary,
)

__all__ = [
    "PBTConfig",
    "PopulationMonitor",
    "apply_level_curriculum",
    "init_population_state",
    "make_population_phase",
    "pbt_exploit_explore",
    "tile_stack",
    "write_population_summary",
]
