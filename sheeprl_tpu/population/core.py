"""In-trace population-based training on the Anakin axis.

The Podracer observation (arXiv:2104.06272) is that a fused Anakin program
leaves one axis spare: ``jax.vmap`` over WHOLE agents — params, opt-state,
per-member env shards and hyperparameters-as-data — turns single-agent
training into population training at the cost of one (bigger) executable,
not N processes.  This module supplies everything algo loops need to do
that, plus in-trace PBT (Jaderberg et al., arXiv:1711.09846):

* **hyperparameters as data** — lr / ent_coef / clip_coef live as ``(P,)``
  device arrays.  The optimizer factory injects every hyperparameter
  (``optax.inject_hyperparams``, utils/optim.py), so a traced per-member lr
  drops straight into the opt-state; clip/ent enter the loss as traced
  arguments.  PR 11's annealing-as-traced-data machinery proved the trick.
* **fitness from the carry** — the Anakin rollout already accumulates
  per-step episode completions (``ep_done``/``ep_ret``); an EMA over each
  member's finished-episode returns is the PBT fitness, computed in-trace
  with zero extra env interaction.
* **exploit/explore without ``lax.cond``** — selection is gated on the
  donated update counter with pure ``jnp.where`` selects: truncation
  selection copies params AND opt-state together from the top members onto
  the bottom members (a ``jnp.take`` gather with a per-member source index
  that is the identity when the gate is closed), then perturbs the copied
  members' hyperparameters by a seeded log-uniform factor.  One trace, one
  executable: ``cache_size()==1`` holds across the whole run and the
  steady state stays zero-H2D under the armed transfer guard.

The difficulty curriculum rides the same axis: every jax env exposes an
``env.level`` knob (docs/jax_envs.md) and the traced-level envs (cartpole,
pendulum, multiroom) carry it as a state leaf, so
:func:`apply_level_curriculum` can pin DIFFERENT difficulties to different
members inside the one executable.  See docs/population.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PBTConfig:
    """Validated snapshot of the ``population`` config group (plus the
    algo's base hyperparameter values the run would use at population=1)."""

    size: int
    exploit_every: int
    warmup: int
    frac: float
    perturb_min: float
    perturb_max: float
    init_min: float
    init_max: float
    bound_min: float
    bound_max: float
    fitness_alpha: float
    levels: Optional[List[float]]
    base: Dict[str, float]

    @classmethod
    def from_cfg(cls, cfg: Any, base: Dict[str, float]) -> "PBTConfig":
        pop = cfg.population
        levels = pop.get("levels")
        self = cls(
            size=int(pop.size),
            exploit_every=int(pop.exploit_every),
            warmup=int(pop.warmup),
            frac=float(pop.frac),
            perturb_min=float(pop.perturb_min),
            perturb_max=float(pop.perturb_max),
            init_min=float(pop.init_min),
            init_max=float(pop.init_max),
            bound_min=float(pop.bound_min),
            bound_max=float(pop.bound_max),
            fitness_alpha=float(pop.fitness_alpha),
            levels=[float(x) for x in levels] if levels else None,
            base={k: float(v) for k, v in base.items()},
        )
        if self.size < 2:
            raise ValueError(f"population.size must be >= 2 to train a population (got {self.size})")
        if not 0.0 < self.frac <= 0.5:
            raise ValueError(f"population.frac must be in (0, 0.5] (got {self.frac})")
        if not 0.0 < self.perturb_min <= self.perturb_max:
            raise ValueError("population.perturb_min/max must satisfy 0 < min <= max")
        if not 0.0 < self.init_min <= self.init_max:
            raise ValueError("population.init_min/max must satisfy 0 < min <= max")
        if not 0.0 < self.bound_min <= self.bound_max:
            raise ValueError("population.bound_min/max must satisfy 0 < min <= max")
        if not 0.0 < self.fitness_alpha <= 1.0:
            raise ValueError("population.fitness_alpha must be in (0, 1]")
        return self

    @property
    def n_select(self) -> int:
        """Truncation width: how many bottom members copy from the top —
        STATIC (shapes one gather), clamped to [1, size // 2]."""
        return max(1, min(self.size // 2, int(round(self.frac * self.size))))

    # -- seeded initial hyperparameter spread --------------------------------
    def init_hyperparams(self, key: jax.Array) -> Dict[str, jax.Array]:
        """Per-member ``(P,)`` arrays: base value × log-uniform factor in
        ``[init_min, init_max]``, clipped to the exploration bounds.  Key
        derivation is positional over the sorted hyperparameter names, so
        the spread is reproducible per seed."""
        hp: Dict[str, jax.Array] = {}
        for i, name in enumerate(sorted(self.base)):
            k = jax.random.fold_in(key, i)
            factor = jnp.exp(
                jax.random.uniform(
                    k, (self.size,),
                    minval=jnp.log(self.init_min), maxval=jnp.log(self.init_max),
                )
            )
            base = self.base[name]
            hp[name] = jnp.clip(
                jnp.float32(base) * factor, base * self.bound_min, base * self.bound_max
            )
        return hp


def tile_stack(tree: Any, size: int) -> Any:
    """Stack ``size`` copies of a pytree along a new leading population
    axis — the fresh-start member params (all members start at the same
    init; the hyperparameter spread is what diversifies them)."""
    return jax.tree.map(lambda x: jnp.stack([x] * size), tree)


def apply_level_curriculum(env_state: Any, levels: List[float], size: int, num_envs: int) -> Any:
    """Pin per-member difficulty levels onto a ``(P, B)``-batched env state.

    Member ``m`` trains at ``levels[m % len(levels)]``; envs carry the level
    as a traced state leaf, and auto-reset preserves the CARRIED level
    (envs/jax/core.py), so the override holds for the whole run.  Raises
    for level-less env states (e.g. forage, whose level is a static shape)
    rather than silently training a flat population.
    """
    if not hasattr(env_state, "level"):
        raise ValueError(
            "population.levels needs an env whose state carries a traced 'level' "
            "leaf (cartpole/pendulum/multiroom); static-level envs (forage) scale "
            "difficulty at construction via env.level instead"
        )
    per_member = jnp.asarray([levels[m % len(levels)] for m in range(size)], jnp.float32)
    return env_state._replace(level=jnp.broadcast_to(per_member[:, None], (size, num_envs)))


def pbt_exploit_explore(
    params: Any,
    opt_state: Any,
    hp: Dict[str, jax.Array],
    fitness: jax.Array,
    do_exploit: jax.Array,
    key: jax.Array,
    pbt: PBTConfig,
):
    """One gated truncation-selection + perturbation step, branch-free.

    ``do_exploit`` is a traced bool (derived from the donated update
    counter); everything below is ``jnp.argsort``/``take``/``where`` — no
    ``lax.cond``, no host sync — so the fused executable keeps ONE cache
    entry whether or not this window exploits.

    * exploit: the ``n_select`` worst members' source index points at the
      ``n_select`` best (worst←best, 2nd-worst←2nd-best, …); everyone else
      points at themselves.  Params and opt-state gather through the SAME
      index, so a copied member gets a coherent (weights, optimizer-moments)
      pair, and the copied member inherits the source's fitness (its old
      score described weights that no longer exist).
    * explore: members whose source differs from themselves perturb every
      hyperparameter by an independent seeded log-uniform factor in
      ``[perturb_min, perturb_max]``, clipped to ``base × [bound_min,
      bound_max]``.

    Returns ``(params, opt_state, hp, fitness, n_copied)`` with ``n_copied``
    the number of members overwritten this call (0 when gated off).
    """
    size, n = pbt.size, pbt.n_select
    idx = jnp.arange(size)
    order = jnp.argsort(fitness)  # ascending: worst first, best last
    # worst i copies best i: order[:n] ← reversed(order[-n:])
    src = idx.at[order[:n]].set(order[size - n :][::-1])
    src = jnp.where(do_exploit, src, idx)
    params = jax.tree.map(lambda x: jnp.take(x, src, axis=0), params)
    opt_state = jax.tree.map(lambda x: jnp.take(x, src, axis=0), opt_state)
    fitness = jnp.take(fitness, src)
    copied = src != idx
    new_hp: Dict[str, jax.Array] = {}
    for i, name in enumerate(sorted(hp)):
        k = jax.random.fold_in(key, i)
        factor = jnp.exp(
            jax.random.uniform(
                k, (size,), minval=jnp.log(pbt.perturb_min), maxval=jnp.log(pbt.perturb_max)
            )
        )
        v = jnp.take(hp[name], src) * jnp.where(copied, factor, 1.0)
        base = pbt.base[name]
        new_hp[name] = jnp.clip(v, base * pbt.bound_min, base * pbt.bound_max)
    n_copied = jnp.where(do_exploit, jnp.int32(n), jnp.int32(0))
    return params, opt_state, new_hp, fitness, n_copied


def init_population_state(members: Dict[str, Any], pbt: PBTConfig, num_envs: int) -> Dict[str, Any]:
    """The population carry around the vmapped member actors: EMA fitness,
    the finished-episode counter that gates the EMA's first observation,
    and the running exploit-event count (all donated alongside the
    members)."""
    if pbt.levels:
        members = dict(members)
        members["env"] = apply_level_curriculum(members["env"], pbt.levels, pbt.size, num_envs)
    return {
        "members": members,
        "fitness": jnp.zeros((pbt.size,), jnp.float32),
        "ep_count": jnp.zeros((pbt.size,), jnp.int32),
        "exploits": jnp.zeros((), jnp.int32),
    }


def make_population_phase(member_phase: Callable, pbt: PBTConfig) -> Callable:
    """Wrap an algo's single-member fused phase into the population phase.

    ``member_phase(p, o_state, actor, key, hp) -> (p, o_state, actor,
    losses, stats)`` is the algo's Anakin rollout+train for ONE member with
    its hyperparameters as traced scalars (``hp`` maps name → scalar).
    The wrapper vmaps it over the population axis, folds the window's
    episode completions into the fitness EMA, and applies the gated PBT
    step — all inside whatever ``fabric.compile`` the caller wraps the
    result in, so the WHOLE population trains in one donated-carry
    executable.

    Returns ``population_phase(params, opt_state, pop, hp, key) ->
    (params, opt_state, pop, hp, key_next, losses, stats)`` where every
    pytree keeps its leading ``(P,)`` axis (losses/stats included — the
    loop reduces for logging).
    """

    def population_phase(params: Any, opt_state: Any, pop: Dict[str, Any], hp: Dict[str, jax.Array], key: jax.Array):
        k_members, k_pbt, k_next = jax.random.split(key, 3)
        member_keys = jax.random.split(k_members, pbt.size)
        params, opt_state, members, losses, stats = jax.vmap(member_phase)(
            params, opt_state, pop["members"], member_keys, hp
        )
        # -- fitness: EMA over each member's finished-episode mean return --
        done = stats["ep_done"].astype(jnp.float32)  # (P, T, B)
        n_done = done.sum(axis=(1, 2))
        mean_ret = (stats["ep_ret"] * done).sum(axis=(1, 2)) / jnp.maximum(n_done, 1.0)
        has_episodes = n_done > 0
        seen_before = pop["ep_count"] > 0
        ema = pbt.fitness_alpha * mean_ret + (1.0 - pbt.fitness_alpha) * pop["fitness"]
        # first observation seeds the EMA directly (an EMA from 0 would
        # bias early selection toward pessimism); no-completion windows
        # leave the score untouched
        fitness = jnp.where(has_episodes, jnp.where(seen_before, ema, mean_ret), pop["fitness"])
        ep_count = pop["ep_count"] + n_done.astype(jnp.int32)

        # -- gated exploit/explore on the donated update counter --
        exploits = pop["exploits"]
        if pbt.exploit_every > 0:  # static: exploit_every=0 removes PBT from the trace
            update = members["update"][0]  # members advance in lockstep
            do_exploit = (update > pbt.warmup) & (update % pbt.exploit_every == 0)
            params, opt_state, hp, fitness, n_copied = pbt_exploit_explore(
                params, opt_state, hp, fitness, do_exploit, k_pbt, pbt
            )
            exploits = exploits + n_copied
        new_pop = {"members": members, "fitness": fitness, "ep_count": ep_count, "exploits": exploits}
        return params, opt_state, new_pop, hp, k_next, losses, stats

    return population_phase


class PopulationMonitor:
    """``Population/*`` telemetry-hub source (hub contract: telemetry/hub.py).

    The loop feeds it host copies of the fitness vector, hyperparameter
    arrays and exploit counter on its logging cadence (D2H pulls — legal
    under the H2D-scoped steady guard, like the episode stats); flushes
    report member fitness spread, cumulative exploit events and the
    hyperparameter quantiles the run is currently exploring.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fitness: Optional[np.ndarray] = None
        self._hp: Dict[str, np.ndarray] = {}
        self._exploits = 0

    def observe(self, fitness: Any, hp: Dict[str, Any], exploits: Any) -> None:
        with self._lock:
            self._fitness = np.asarray(fitness, np.float64)
            self._hp = {k: np.asarray(v, np.float64) for k, v in hp.items()}
            self._exploits = int(exploits)

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            fitness, hp, exploits = self._fitness, self._hp, self._exploits
        if fitness is None:
            return {}
        out = {
            "Population/fitness_best": float(fitness.max()),
            "Population/fitness_worst": float(fitness.min()),
            "Population/fitness_spread": float(fitness.max() - fitness.min()),
            "Population/exploit_events": float(exploits),
        }
        for name, values in hp.items():
            out[f"Population/{name}_p10"] = float(np.quantile(values, 0.10))
            out[f"Population/{name}_p50"] = float(np.quantile(values, 0.50))
            out[f"Population/{name}_p90"] = float(np.quantile(values, 0.90))
        return out


def write_population_summary(
    log_dir: str,
    pop: Dict[str, Any],
    hp: Dict[str, jax.Array],
    policy_step: int,
) -> str:
    """Land the run's final population snapshot as
    ``<log_dir>/population_summary.json`` — the machine-readable artifact
    the run_ci PBT drill (stage 18) and bench ``--mode population`` read
    to compare members across runs."""
    fitness = np.asarray(pop["fitness"], np.float64)
    summary = {
        "policy_step": int(policy_step),
        "fitness": [float(x) for x in fitness],
        "best_member": int(fitness.argmax()),
        "worst_member": int(fitness.argmin()),
        "best_fitness": float(fitness.max()),
        "worst_fitness": float(fitness.min()),
        "episodes_per_member": [int(x) for x in np.asarray(pop["ep_count"])],
        "exploit_events": int(np.asarray(pop["exploits"])),
        "hyperparams": {k: [float(x) for x in np.asarray(v)] for k, v in sorted(hp.items())},
    }
    path = os.path.join(log_dir, "population_summary.json")
    with open(path, "w") as fh:
        json.dump(summary, fh, indent=2)
    return path
