"""DIAMBRA arcade (fighting games) suite wrapper.

Behavior parity with the reference wrapper (reference:
sheeprl/envs/diambra.py:22-146):

- assembles DIAMBRA ``EnvironmentSettings`` / ``WrappersSettings`` from the
  config (forcing 1 player, flattened obs, and the requested action space),
  warning about and dropping settings this framework manages itself
  (frame shape, stacking, dilation are handled by the shared wrapper
  pipeline in ``utils/env.py``);
- converts the backend observation space to a flat ``Dict`` of ``Box``
  spaces: Discrete → Box(shape=(1,)) int32, MultiDiscrete → Box(shape=(n,))
  int32, Box passthrough — so every algorithm sees a uniform dict-of-arrays
  interface;
- reshapes every observation to the advertised shape and stamps
  ``info["env_domain"] = "DIAMBRA"``;
- a round/stage end signalled via ``info["env_done"]`` counts as an episode
  termination.

The backend (``diambra`` + its docker engine) is not available in this
image; construction goes through :func:`_make_backend` so tests can run the
conversion logic against a mock arena.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple, Union

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_DIAMBRA_AVAILABLE

_MANAGED_SETTINGS = ("frame_shape", "n_players")
_MANAGED_WRAPPERS = ("frame_shape", "stack_frames", "dilation", "flatten")


def _make_backend(
    env_id: str,
    action_space: str,
    screen_size: Tuple[int, int],
    grayscale: bool,
    repeat_action: int,
    rank: int,
    diambra_settings: Dict[str, Any],
    diambra_wrappers: Dict[str, Any],
    render_mode: str,
    log_level: int,
    increase_performance: bool,
) -> Any:
    """Assemble settings and build the raw DIAMBRA arena env."""
    if not _IS_DIAMBRA_AVAILABLE:
        raise ImportError(
            "DIAMBRA environments need the 'diambra' + 'diambra-arena' packages "
            "and the DIAMBRA docker engine; they are not available in this image"
        )
    import diambra.arena  # type: ignore
    from diambra.arena import EnvironmentSettings, WrappersSettings  # type: ignore

    role = diambra_settings.pop("role", None)
    if repeat_action > 1:
        # Sticky actions and the engine's internal frame skipping compose
        # multiplicatively; force step_ratio=1 so action_repeat means frames.
        if diambra_settings.get("step_ratio", 6) > 1:
            warnings.warn(
                f"step_ratio set to 1 because action repeat is active ({repeat_action})"
            )
        diambra_settings["step_ratio"] = 1
    settings = EnvironmentSettings(
        **{
            **diambra_settings,
            "game_id": env_id,
            "action_space": getattr(
                diambra.arena.SpaceTypes, action_space, diambra.arena.SpaceTypes.DISCRETE
            ),
            "n_players": 1,
            "role": getattr(diambra.arena.Roles, role) if role is not None else None,
            "render_mode": render_mode,
        }
    )
    wrappers = WrappersSettings(
        **{**diambra_wrappers, "flatten": True, "repeat_action": repeat_action}
    )
    # Resizing inside the engine (settings) is cheaper than in the wrapper
    # pipeline; increase_performance selects where the frame is shaped.
    frame_shape = tuple(screen_size) + (int(grayscale),)
    if increase_performance:
        settings.frame_shape = frame_shape
    else:
        wrappers.frame_shape = frame_shape
    return diambra.arena.make(
        env_id, settings, wrappers, rank=rank, render_mode=render_mode, log_level=log_level
    )


def _flatten_obs_space(backend_space: Any) -> spaces.Dict:
    """Map every sub-space to a Box so downstream code sees uniform arrays."""
    out: Dict[str, spaces.Space] = {}
    for key, sub in backend_space.spaces.items():
        if isinstance(sub, spaces.Box):
            out[key] = sub
        elif isinstance(sub, spaces.Discrete):
            out[key] = spaces.Box(0, int(sub.n) - 1, (1,), np.int32)
        elif isinstance(sub, spaces.MultiDiscrete):
            nvec = np.asarray(sub.nvec)
            out[key] = spaces.Box(np.zeros_like(nvec), nvec - 1, (len(nvec),), np.int32)
        else:
            raise RuntimeError(f"Unsupported DIAMBRA observation space: {type(sub)}")
    return spaces.Dict(out)


class DiambraWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        action_space: str = "DISCRETE",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ) -> None:
        if action_space not in ("DISCRETE", "MULTI_DISCRETE"):
            raise ValueError(
                "action_space must be 'DISCRETE' or 'MULTI_DISCRETE', "
                f"got {action_space!r}"
            )
        diambra_settings = dict(diambra_settings or {})
        diambra_wrappers = dict(diambra_wrappers or {})
        role = diambra_settings.get("role")
        if role is not None and role not in ("P1", "P2"):
            raise ValueError(f"role must be 'P1', 'P2' or None, got {role!r}")
        for key in _MANAGED_SETTINGS:
            if diambra_settings.pop(key, None) is not None:
                warnings.warn(f"The DIAMBRA '{key}' setting is managed by the framework")
        for key in _MANAGED_WRAPPERS:
            if diambra_wrappers.pop(key, None) is not None:
                warnings.warn(f"The DIAMBRA '{key}' wrapper is managed by the framework")
        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)

        self._action_type = action_space.lower()
        self.env = _make_backend(
            id,
            action_space,
            screen_size,
            grayscale,
            repeat_action,
            rank,
            diambra_settings,
            diambra_wrappers,
            render_mode,
            log_level,
            increase_performance,
        )
        self.action_space = self.env.action_space
        self.observation_space = _flatten_obs_space(self.env.observation_space)
        self._render_mode = render_mode

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            k: np.asarray(v).reshape(self.observation_space[k].shape) for k, v in obs.items()
        }

    def step(self, action: Any) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        if self._action_type == "discrete" and isinstance(action, np.ndarray):
            action = int(action.squeeze().item())
        obs, reward, terminated, truncated, info = self.env.step(action)
        info["env_domain"] = "DIAMBRA"
        terminated = bool(terminated) or bool(info.get("env_done", False))
        return self._convert_obs(obs), float(reward), terminated, bool(truncated), info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        obs, info = self.env.reset(seed=seed, options=options)
        info["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), info

    def render(self) -> Optional[np.ndarray]:
        return self.env.render()

    def close(self) -> None:
        self.env.close()
