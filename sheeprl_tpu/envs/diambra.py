"""DIAMBRA arcade wrapper (reference: sheeprl/envs/diambra.py:22). Gated."""

from __future__ import annotations

from typing import Any

try:
    import diambra.arena  # type: ignore  # noqa: F401

    _DIAMBRA_AVAILABLE = True
except Exception:
    _DIAMBRA_AVAILABLE = False


class DiambraWrapper:
    def __init__(self, *args: Any, **kwargs: Any):
        if not _DIAMBRA_AVAILABLE:
            raise ImportError(
                "DIAMBRA environments need the 'diambra-arena' package and its "
                "docker engine; they are not available in this image"
            )
        raise NotImplementedError(
            "DIAMBRA support is declared but not yet implemented in this build"
        )
