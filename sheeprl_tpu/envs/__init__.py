"""sheeprl_tpu.envs."""
