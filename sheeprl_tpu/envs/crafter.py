"""Crafter wrapper (reference: sheeprl/envs/crafter.py:17+). Gated."""

from __future__ import annotations

from typing import Optional

import gymnasium as gym
import numpy as np
from gymnasium import spaces

try:
    import crafter  # type: ignore

    _CRAFTER_AVAILABLE = True
except Exception:
    _CRAFTER_AVAILABLE = False


class CrafterWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(self, env_id: str = "reward", screen_size: int = 64, seed: Optional[int] = None):
        if not _CRAFTER_AVAILABLE:
            raise ImportError(
                "Crafter needs the 'crafter' package; it is not available in this image"
            )
        self._env = crafter.Env(size=(screen_size, screen_size), reward=(env_id != "nonreward"), seed=seed)
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(0, 255, (screen_size, screen_size, 3), np.uint8)}
        )
        self.action_space = spaces.Discrete(self._env.action_space.n)

    def reset(self, *, seed=None, options=None):
        obs = self._env.reset()
        return {"rgb": np.asarray(obs, np.uint8)}, {}

    def step(self, action):
        obs, reward, done, info = self._env.step(int(action))
        return {"rgb": np.asarray(obs, np.uint8)}, float(reward), bool(done), False, info

    def render(self):
        return self._env.render()
