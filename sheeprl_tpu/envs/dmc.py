"""DeepMind Control Suite wrapper (reference: sheeprl/envs/dmc.py:49+).

Wraps a dm_control task as a gymnasium env with a Dict observation space:
proprioceptive readings flattened under ``state`` and (optionally) rendered
pixels under ``rgb``.  Gated on ``dm_control`` availability.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import gymnasium as gym
import numpy as np
from gymnasium import spaces

# Headless default: TPU VMs have no X display; MuJoCo's default glfw backend
# needs one.  EGL renders headless on CPU/GPU alike — pick it before the
# first dm_control import unless the user chose a backend themselves.
# Linux-only: macOS has no EGL (MuJoCo uses cgl there without DISPLAY).
import sys as _sys

if (
    _sys.platform.startswith("linux")
    and "MUJOCO_GL" not in os.environ
    and not os.environ.get("DISPLAY")
):
    os.environ["MUJOCO_GL"] = "egl"

try:
    from dm_control import suite  # type: ignore

    _DMC_AVAILABLE = True
except Exception:
    _DMC_AVAILABLE = False


class DMCWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(
        self,
        env_id: str,
        seed: Optional[int] = None,
        from_pixels: bool = True,
        from_vectors: bool = False,
        width: int = 64,
        height: int = 64,
        camera_id: int = 0,
    ):
        if not _DMC_AVAILABLE:
            raise ImportError(
                "DMC environments need the 'dm_control' package; it is not "
                "available in this image"
            )
        domain, task = env_id.replace("_", " ").split(" ", 1) if "_" in env_id else env_id.split("-", 1)
        self._env = suite.load(domain, task.replace(" ", "_"), task_kwargs={"random": seed})
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        self._width, self._height, self._camera = width, height, camera_id

        act_spec = self._env.action_spec()
        self.action_space = spaces.Box(
            act_spec.minimum.astype(np.float32), act_spec.maximum.astype(np.float32)
        )
        obs_spaces: Dict[str, spaces.Space] = {}
        if from_pixels:
            obs_spaces["rgb"] = spaces.Box(0, 255, (height, width, 3), np.uint8)
        if from_vectors or not from_pixels:
            dim = int(sum(np.prod(v.shape) for v in self._env.observation_spec().values()))
            obs_spaces["state"] = spaces.Box(-np.inf, np.inf, (dim,), np.float32)
        self.observation_space = spaces.Dict(obs_spaces)

    def _obs(self, timestep) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        if self._from_pixels:
            out["rgb"] = self.render()
        if "state" in self.observation_space.spaces:
            out["state"] = np.concatenate(
                [np.asarray(v, np.float32).reshape(-1) for v in timestep.observation.values()]
            )
        return out

    def reset(self, *, seed=None, options=None):
        timestep = self._env.reset()
        return self._obs(timestep), {}

    def step(self, action):
        timestep = self._env.step(np.asarray(action))
        reward = float(timestep.reward or 0.0)
        terminated = timestep.last() and timestep.discount == 0.0
        truncated = timestep.last() and not terminated
        return self._obs(timestep), reward, terminated, truncated, {}

    def render(self) -> np.ndarray:
        return self._env.physics.render(self._height, self._width, camera_id=self._camera)
