"""Pure-JAX CartPole, transition-exact against Gymnasium ``CartPole-v1``.

Dynamics, constants, termination thresholds and reward are copied from
gymnasium's ``classic_control/cartpole.py`` (Euler integration, 12° pole /
2.4m cart limits, +1 reward every step including the terminating one) so
seeded transition-parity tests can assert equality within float tolerance
(tests/test_envs/test_jax_envs.py).  The 500-step ``TimeLimit`` wrapper of
the gym registration becomes an in-env ``truncated`` flag — inside a
``lax.scan`` there is no wrapper to do it.

Reset draws all four state components from U(-0.05, 0.05) like gymnasium;
the PRNG differs (threefry vs PCG64), so parity tests pin transitions from
explicit states rather than comparing seeded reset draws.

Difficulty axis (``env.level``, docs/jax_envs.md): ``level`` lives as a
TRACED scalar in the state pytree — gravity scales by ``1 + 0.5·level``
and the pole half-length by ``1 + level``, so a vmapped population can
train members at different difficulties inside one executable.  At the
default ``level=0`` every multiplier is exactly ``1.0`` (bit-exact in
float32), so transitions stay bit-identical to the gymnasium-parity
dynamics above.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs.jax.core import JaxEnv, Obs


class CartPoleState(NamedTuple):
    x: jax.Array  # cart position
    x_dot: jax.Array  # cart velocity
    theta: jax.Array  # pole angle (rad)
    theta_dot: jax.Array  # pole angular velocity
    t: jax.Array  # step counter (int32)
    key: jax.Array  # per-instance PRNG stream
    level: jax.Array = 0.0  # traced difficulty (gravity / pole length)


class JaxCartPole(JaxEnv):
    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSPOLE + MASSCART
    LENGTH = 0.5  # half the pole's length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * math.pi / 360
    X_THRESHOLD = 2.4

    def __init__(self, max_episode_steps: int = 500, level: float = 0.0):
        self.max_episode_steps = int(max_episode_steps)
        self.level = float(level)
        high = np.array(
            [self.X_THRESHOLD * 2, np.inf, self.THETA_THRESHOLD * 2, np.inf],
            dtype=np.float32,
        )
        self.observation_space = spaces.Dict({"state": spaces.Box(-high, high, dtype=np.float32)})
        self.action_space = spaces.Discrete(2)

    def reset(self, key: jax.Array) -> Tuple[CartPoleState, Obs]:
        k_init, k_carry = jax.random.split(key)
        init = jax.random.uniform(k_init, (4,), minval=-0.05, maxval=0.05, dtype=jnp.float32)
        state = CartPoleState(
            x=init[0], x_dot=init[1], theta=init[2], theta_dot=init[3],
            t=jnp.zeros((), jnp.int32), key=k_carry,
            level=jnp.full((), self.level, jnp.float32),
        )
        return state, self.observe(state)

    def observe(self, state: CartPoleState) -> Obs:
        return {
            "state": jnp.stack([state.x, state.x_dot, state.theta, state.theta_dot]).astype(
                jnp.float32
            )
        }

    def step(self, state: CartPoleState, action: jax.Array):
        # difficulty-derived constants, in-trace: ×(1.0) exactly at level=0,
        # so the default level reproduces the gymnasium dynamics bit-for-bit
        lvl = jnp.asarray(state.level, jnp.float32)
        gravity = self.GRAVITY * (1.0 + 0.5 * lvl)
        length = self.LENGTH * (1.0 + lvl)
        polemass_length = self.MASSPOLE * length
        force = jnp.where(action.astype(jnp.int32) == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = jnp.cos(state.theta)
        sintheta = jnp.sin(state.theta)
        # gymnasium's Euler step, verbatim (constants swapped for the traced
        # level-derived ones above)
        temp = (force + polemass_length * state.theta_dot**2 * sintheta) / self.TOTAL_MASS
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / self.TOTAL_MASS)
        )
        xacc = temp - polemass_length * thetaacc * costheta / self.TOTAL_MASS
        x = state.x + self.TAU * state.x_dot
        x_dot = state.x_dot + self.TAU * xacc
        theta = state.theta + self.TAU * state.theta_dot
        theta_dot = state.theta_dot + self.TAU * thetaacc
        t = state.t + 1

        terminated = (
            (jnp.abs(x) > self.X_THRESHOLD) | (jnp.abs(theta) > self.THETA_THRESHOLD)
        )
        truncated = jnp.logical_and(t >= self.max_episode_steps, jnp.logical_not(terminated))
        new_state = CartPoleState(
            x=x, x_dot=x_dot, theta=theta, theta_dot=theta_dot, t=t, key=state.key, level=state.level
        )
        return (
            new_state,
            self.observe(new_state),
            jnp.float32(1.0),  # +1 every step, including the terminating one
            terminated,
            truncated,
        )
