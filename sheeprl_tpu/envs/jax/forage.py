"""Procedural pixel foraging gridworld — the CNN-path exercise env.

A ``grid × grid`` world rendered fully in-trace onto an ``(H, W, 3)`` uint8
image (channel-last, the TPU-native layout): the agent is a white cell,
food cells are green.  Each reset procedurally scatters ``n_food`` food
cells and the agent start from the instance's PRNG key (a permutation of
the cell grid, so placements never collide).  Actions are
noop/up/down/left/right; eating a food cell pays +1; the episode
*terminates* when all food is eaten and *truncates* at
``max_episode_steps`` — so both gymnasium end-of-episode flags get real
coverage on the pixel path.

The position and remaining food appear ONLY in the pixels (no state
vector), so a policy can beat random exclusively through its CNN trunk —
same design teeth as ``PixelGridDummyEnv``, but pure-JAX and procedurally
seeded per episode.

Difficulty axis (``env.level``, docs/jax_envs.md): the grid size is a
STATIC array shape, so forage's level is resolved at construction — each
whole level *doubles* the grid (while the image stays divisible), keeping
the same ``n_food`` count on a larger board, i.e. a lower food density and
a harder search problem.  ``level=0`` leaves the configured geometry
untouched (bit-identical).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs.jax.core import JaxEnv, Obs

# noop/up/down/left/right
_MOVES = np.array([[0, 0], [-1, 0], [1, 0], [0, -1], [0, 1]], dtype=np.int32)


class ForageState(NamedTuple):
    pos: jax.Array  # (2,) int32 agent cell (row, col)
    food: jax.Array  # (grid, grid) bool remaining food
    t: jax.Array  # step counter (int32)
    key: jax.Array  # per-instance PRNG stream


class JaxForage(JaxEnv):
    def __init__(
        self,
        grid: int = 8,
        n_food: int = 6,
        image_hw: int = 64,
        max_episode_steps: int = 128,
        level: float = 0.0,
    ):
        self.level = float(level)
        # static difficulty: each whole level doubles the grid (same food
        # count on a bigger board = lower density) while the image stays an
        # exact multiple of the cell size
        grid = int(grid)
        for _ in range(max(0, int(self.level))):
            if grid * 2 <= image_hw and image_hw % (grid * 2) == 0:
                grid *= 2
        if image_hw % grid != 0:
            raise ValueError(f"image_hw ({image_hw}) must be a multiple of grid ({grid})")
        if n_food >= grid * grid:
            raise ValueError(f"n_food ({n_food}) must leave room for the agent on a {grid}x{grid} grid")
        self.grid = int(grid)
        self.n_food = int(n_food)
        self.image_hw = int(image_hw)
        self.cell = self.image_hw // self.grid
        self.max_episode_steps = int(max_episode_steps)
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(0, 255, (image_hw, image_hw, 3), np.uint8)}
        )
        self.action_space = spaces.Discrete(5)

    def reset(self, key: jax.Array) -> Tuple[ForageState, Obs]:
        k_place, k_carry = jax.random.split(key)
        # one permutation of the cell grid: slot 0 is the agent, the next
        # n_food slots are food — procedural placement with no collisions
        cells = jax.random.permutation(k_place, self.grid * self.grid)
        agent = cells[0]
        pos = jnp.stack([agent // self.grid, agent % self.grid]).astype(jnp.int32)
        food = (
            jnp.zeros((self.grid * self.grid,), bool)
            .at[cells[1 : 1 + self.n_food]]
            .set(True)
            .reshape(self.grid, self.grid)
        )
        state = ForageState(pos=pos, food=food, t=jnp.zeros((), jnp.int32), key=k_carry)
        return state, self.observe(state)

    def observe(self, state: ForageState) -> Obs:
        # (G, G, 3) uint8 cell image: green food, white agent (agent wins
        # the cell it stands on), upsampled to (H, W, 3) by pixel repeat
        food = state.food[..., None] * jnp.array([0, 255, 0], jnp.uint8)
        agent = (
            jnp.zeros((self.grid, self.grid), bool)
            .at[state.pos[0], state.pos[1]]
            .set(True)
        )
        img = jnp.where(agent[..., None], jnp.uint8(255), food)
        img = jnp.repeat(jnp.repeat(img, self.cell, axis=0), self.cell, axis=1)
        return {"rgb": img}

    def step(self, state: ForageState, action: jax.Array):
        move = jnp.asarray(_MOVES)[action.astype(jnp.int32) % 5]
        pos = jnp.clip(state.pos + move, 0, self.grid - 1)
        ate = state.food[pos[0], pos[1]]
        food = state.food.at[pos[0], pos[1]].set(False)
        t = state.t + 1
        new_state = ForageState(pos=pos, food=food, t=t, key=state.key)
        terminated = ~jnp.any(food)
        truncated = jnp.logical_and(t >= self.max_episode_steps, jnp.logical_not(terminated))
        return (
            new_state,
            self.observe(new_state),
            ate.astype(jnp.float32),
            terminated,
            truncated,
        )
