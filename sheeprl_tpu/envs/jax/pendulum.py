"""Pure-JAX Pendulum, transition-exact against Gymnasium ``Pendulum-v1``.

Constants and dynamics follow gymnasium's ``classic_control/pendulum.py``
(g=10.0 default, semi-implicit Euler with speed clipping, quadratic cost on
normalized angle / speed / torque).  The 200-step TimeLimit becomes an
in-env ``truncated`` flag; the env never terminates.

Difficulty axis (``env.level``, docs/jax_envs.md): ``level`` is a TRACED
scalar in the state pytree shrinking the effective torque limit to
``MAX_TORQUE / (1 + level)`` — a weaker motor needs energy-pumping swings.
The action space stays fixed at ``±MAX_TORQUE`` (spaces are static across
levels); actions are clipped harder in-step.  ``level=0`` divides by
exactly ``1.0``, keeping transitions bit-identical to the parity-tested
dynamics.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs.jax.core import JaxEnv, Obs


def angle_normalize(x: jax.Array) -> jax.Array:
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array  # step counter (int32)
    key: jax.Array  # per-instance PRNG stream
    level: jax.Array = 0.0  # traced difficulty (torque limit)


class JaxPendulum(JaxEnv):
    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, max_episode_steps: int = 200, g: float = 10.0, level: float = 0.0):
        self.max_episode_steps = int(max_episode_steps)
        self.g = float(g)
        self.level = float(level)
        high = np.array([1.0, 1.0, self.MAX_SPEED], dtype=np.float32)
        self.observation_space = spaces.Dict({"state": spaces.Box(-high, high, dtype=np.float32)})
        self.action_space = spaces.Box(-self.MAX_TORQUE, self.MAX_TORQUE, (1,), np.float32)

    def reset(self, key: jax.Array) -> Tuple[PendulumState, Obs]:
        k_init, k_carry = jax.random.split(key)
        init = jax.random.uniform(
            k_init, (2,),
            minval=jnp.array([-math.pi, -1.0]),
            maxval=jnp.array([math.pi, 1.0]),
            dtype=jnp.float32,
        )
        state = PendulumState(
            theta=init[0], theta_dot=init[1], t=jnp.zeros((), jnp.int32), key=k_carry,
            level=jnp.full((), self.level, jnp.float32),
        )
        return state, self.observe(state)

    def observe(self, state: PendulumState) -> Obs:
        return {
            "state": jnp.stack(
                [jnp.cos(state.theta), jnp.sin(state.theta), state.theta_dot]
            ).astype(jnp.float32)
        }

    def step(self, state: PendulumState, action: jax.Array):
        # traced torque limit: ÷(1.0) exactly at level=0 (bit-identical)
        max_torque = self.MAX_TORQUE / (1.0 + jnp.asarray(state.level, jnp.float32))
        u = jnp.clip(action.reshape(()), -max_torque, max_torque)
        th, thdot = state.theta, state.theta_dot
        costs = angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (
            3.0 * self.g / (2.0 * self.L) * jnp.sin(th) + 3.0 / (self.M * self.L**2) * u
        ) * self.DT
        newthdot = jnp.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT
        t = state.t + 1
        new_state = PendulumState(
            theta=newth, theta_dot=newthdot, t=t, key=state.key, level=state.level
        )
        return (
            new_state,
            self.observe(new_state),
            -costs.astype(jnp.float32),
            jnp.zeros((), bool),  # pendulum never terminates
            t >= self.max_episode_steps,
        )
