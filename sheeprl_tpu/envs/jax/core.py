"""On-device vectorized environments: the pure-function env contract.

The Anakin pattern (Podracer, arXiv:2104.06272) puts the environment INSIDE
the jitted step so one chip steps thousands of env instances with zero host
round-trips — the structural fix for the honest negative in BENCH_TPU.md
(PPO/SAC classic-control ran *slower* on-chip because the chip idled while
Python gym workers stepped envs and shipped observations).

Env authoring contract (docs/jax_envs.md):

* **State is an explicit pytree** — a ``NamedTuple`` whose leaves are JAX
  arrays, carrying EVERYTHING the env needs between steps, including a
  ``key`` field holding the instance's own PRNG stream.  No Python-side
  state; ``step``/``reset`` are pure, jit-traceable functions.
* ``reset(key) -> (state, obs)`` — consumes the key (storing a derived
  carry key in ``state.key`` for later stochasticity/auto-reset reseeds).
* ``step(state, action) -> (state, obs, reward, terminated, truncated)`` —
  single-instance semantics; gymnasium flag split (``terminated`` = MDP
  terminal state, ``truncated`` = time/step limit).  Truncation is the
  env's own job here (there is no ``TimeLimit`` wrapper inside a scan).
* ``observe(state) -> obs`` — the deterministic state→observation map,
  exposed separately so rollout scans can read the *current* obs without
  stepping (and so ``step`` need not return redundant copies).
* Observations are ``Dict[str, Array]`` matching ``observation_space``
  (a ``gym.spaces.Dict``): vectors under ``"state"`` (float32), images
  under ``"rgb"`` (uint8 ``(H, W, C)`` — the TPU-native channel-last
  layout used framework-wide).

:class:`VectorJaxEnv` batches any such env over ``num_envs`` instances with
``jax.vmap`` and implements gymnasium's SAME_STEP auto-reset semantics: when
an instance finishes, the same step returns the *reset* observation while
the true terminal observation is surfaced separately (``final_obs``) for
truncation bootstrapping — exactly the ``info["final_obs"]`` contract of
the ``AsyncVectorEnv`` path, but as traced arrays.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Obs = Dict[str, jax.Array]


class JaxEnv:
    """Base class for pure-JAX environments (see module docstring for the
    authoring contract).  Subclasses define gymnasium ``observation_space``
    / ``action_space`` (single-instance) plus the three pure functions."""

    observation_space: Any
    action_space: Any
    #: per-episode step limit driving the ``truncated`` flag (None = never)
    max_episode_steps: Optional[int] = None

    def reset(self, key: jax.Array) -> Tuple[Any, Obs]:
        raise NotImplementedError

    def step(self, state: Any, action: jax.Array) -> Tuple[Any, Obs, jax.Array, jax.Array, jax.Array]:
        raise NotImplementedError

    def observe(self, state: Any) -> Obs:
        raise NotImplementedError


class VectorJaxEnv:
    """``num_envs`` instances of a :class:`JaxEnv` as one batched pure
    function, with SAME_STEP auto-reset.

    Every method is jit-traceable; the batched ``EnvState`` pytree has
    leading dimension ``num_envs`` on every leaf and can be sharded over
    the mesh ``data`` axis (``fabric.shard_batch(state, axis=0)``) so env
    stepping parallelizes with the train step it is fused into.
    """

    def __init__(self, env: JaxEnv, num_envs: int):
        self.env = env
        self.num_envs = int(num_envs)
        self.single_observation_space = env.observation_space
        self.single_action_space = env.action_space
        self._vreset = jax.vmap(env.reset)
        self._vobserve = jax.vmap(env.observe)
        self._vstep = jax.vmap(self._autoreset_step)

    # -- single-instance auto-reset (vmapped) ------------------------------
    def _autoreset_step(self, state: Any, action: jax.Array):
        env = self.env
        s1, obs1, reward, terminated, truncated = env.step(state, action)
        done = jnp.logical_or(terminated, truncated)
        # the reset consumes a key derived from the instance's own stream —
        # split unconditionally so the trace is branch-free and the carry
        # key advances every step regardless of done
        k_reset, k_carry = jax.random.split(s1.key)
        s1 = s1._replace(key=k_carry)
        s_reset, obs_reset = env.reset(k_reset)
        if hasattr(s_reset, "level"):
            # the difficulty level rides the CARRY, not the reset: a
            # curriculum-overridden traced level (docs/population.md) must
            # survive episode boundaries, and ``env.reset`` only knows the
            # static default.  Bitwise no-op when nothing overrode it.
            s_reset = s_reset._replace(level=s1.level)
            obs_reset = env.observe(s_reset)
        s2 = jax.tree.map(lambda a, b: jnp.where(done, a, b), s_reset, s1)
        obs_out = jax.tree.map(lambda a, b: jnp.where(done, a, b), obs_reset, obs1)
        # obs1 is the TRUE final observation of the finished episode — the
        # vector-env `final_obs` contract, needed for truncation bootstraps
        return s2, obs_out, reward, terminated, truncated, obs1

    # -- batched API -------------------------------------------------------
    def reset(self, key: jax.Array) -> Tuple[Any, Obs]:
        """Batched reset: one derived key per instance."""
        return self._vreset(jax.random.split(key, self.num_envs))

    def step(self, state: Any, actions: jax.Array):
        """``(state, obs, reward, terminated, truncated, final_obs)`` —
        SAME_STEP auto-reset: finished rows come back already reset (their
        true terminal obs in ``final_obs``)."""
        return self._vstep(state, actions)

    def observe(self, state: Any) -> Obs:
        return self._vobserve(state)
