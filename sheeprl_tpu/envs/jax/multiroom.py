"""Procedural multi-room pixel gridworld — doors, keys, food, one goal.

A Crafter-lite for the pure-JAX suite: every episode procedurally generates
a new layout **in-trace** from the instance's PRNG stream (no host-side
level generator, no layout tables) — door rows, key cells, food scatter,
agent start and goal are all drawn at ``reset`` and live in the state
pytree.  The world is a ``grid × grid`` board split into rooms by vertical
walls at fixed columns; each wall has one door, locked until the agent
steps on that wall's key (placed somewhere left of the wall, so rooms are
always solved in order and every episode is completable).  Food pellets
pay +0.1, a key pickup +0.2, and reaching the goal cell in the last room
pays +1.0 and **terminates** the episode; ``max_episode_steps`` truncates.

Everything the agent needs is in the pixels (walls gray, closed doors red,
open doors dark gray, keys yellow, food green, goal blue, agent white) —
like :class:`~sheeprl_tpu.envs.jax.forage.JaxForage` this is a CNN-trunk
exercise env, but with longer-horizon structure (unlock-progression).

Difficulty axis (``env.level``, docs/jax_envs.md): ``level`` is a TRACED
scalar in the state pytree selecting the active room count — ``1 +
floor(level)`` walls (clamped to 3), i.e. 2 rooms at the default
``level=0`` up to 4 rooms at ``level>=2``.  Inactive walls render (and
collide) as open floor.  Because the room count is data, a vmapped
population can train members across a difficulty curriculum inside ONE
fused executable (docs/population.md).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs.jax.core import JaxEnv, Obs

# noop/up/down/left/right — the forage action set
_MOVES = np.array([[0, 0], [-1, 0], [1, 0], [0, -1], [0, 1]], dtype=np.int32)

_WALL_RGB = np.array([128, 128, 128], np.uint8)
_DOOR_RGB = np.array([200, 0, 0], np.uint8)  # locked
_OPEN_RGB = np.array([60, 60, 60], np.uint8)  # unlocked passage
_KEY_RGB = np.array([255, 255, 0], np.uint8)
_FOOD_RGB = np.array([0, 255, 0], np.uint8)
_GOAL_RGB = np.array([0, 0, 255], np.uint8)
_AGENT_RGB = np.array([255, 255, 255], np.uint8)

#: maximum wall count (4 rooms); walls sit at fixed fractions of the board
_MAX_WALLS = 3


class MultiRoomState(NamedTuple):
    pos: jax.Array  # (2,) int32 agent cell (row, col)
    door_row: jax.Array  # (3,) int32 door row per wall (procedural)
    door_open: jax.Array  # (3,) bool unlocked doors
    key_taken: jax.Array  # (3,) bool collected keys
    key_pos: jax.Array  # (3, 2) int32 key cells (procedural)
    food: jax.Array  # (grid, grid) bool remaining food
    goal: jax.Array  # (2,) int32 goal cell (last column)
    t: jax.Array  # step counter (int32)
    key: jax.Array  # per-instance PRNG stream
    level: jax.Array = 0.0  # traced difficulty (active room count)


class JaxMultiRoom(JaxEnv):
    def __init__(
        self,
        grid: int = 8,
        n_food: int = 4,
        image_hw: int = 64,
        max_episode_steps: int = 256,
        level: float = 0.0,
    ):
        grid = int(grid)
        if grid < 8:
            raise ValueError(f"grid ({grid}) must be >= 8 to fit 4 rooms")
        if image_hw % grid != 0:
            raise ValueError(f"image_hw ({image_hw}) must be a multiple of grid ({grid})")
        self.grid = grid
        self.n_food = int(n_food)
        self.image_hw = int(image_hw)
        self.cell = self.image_hw // self.grid
        self.max_episode_steps = int(max_episode_steps)
        self.level = float(level)
        # fixed wall columns at quarter points: 2/4/6 on the default 8-grid
        self.wall_cols = (grid // 4, grid // 2, (3 * grid) // 4)
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(0, 255, (image_hw, image_hw, 3), np.uint8)}
        )
        self.action_space = spaces.Discrete(5)

    # -- helpers -----------------------------------------------------------
    def _n_walls(self, level: jax.Array) -> jax.Array:
        """Active wall count from the traced level: 1 + floor(level), in
        [1, 3] — two rooms at level 0, four at level >= 2."""
        lvl = jnp.asarray(level, jnp.float32)
        return 1 + jnp.clip(jnp.floor(lvl).astype(jnp.int32), 0, _MAX_WALLS - 1)

    def _off_wall(self, cols: jax.Array) -> jax.Array:
        """Shift procedural columns off the (even) wall columns: every wall
        column minus one is a valid floor column."""
        on_wall = jnp.zeros(cols.shape, bool)
        for c in self.wall_cols:
            on_wall = on_wall | (cols == c)
        return jnp.where(on_wall, cols - 1, cols)

    # -- contract ----------------------------------------------------------
    def reset(self, key: jax.Array) -> Tuple[MultiRoomState, Obs]:
        g = self.grid
        k_door, k_start, k_goal, k_krow, k_kcol, k_frow, k_fcol, k_carry = jax.random.split(key, 8)
        door_row = jax.random.randint(k_door, (_MAX_WALLS,), 0, g)
        start_row = jax.random.randint(k_start, (), 0, g)
        goal_row = jax.random.randint(k_goal, (), 0, g)
        # key w lives strictly LEFT of wall w (rooms unlock in order; every
        # layout is completable): draw col in [0, wall_col) and shift off
        # any wall column (col-1 is always floor and still < wall_col)
        key_row = jax.random.randint(k_krow, (_MAX_WALLS,), 0, g)
        key_col = self._off_wall(
            jax.random.randint(k_kcol, (_MAX_WALLS,), 0, jnp.asarray(self.wall_cols))
        )
        key_pos = jnp.stack([key_row, key_col], axis=1).astype(jnp.int32)
        # food scatter anywhere on floor (overlaps with keys/goal are
        # harmless: both payoffs trigger on the shared cell)
        food_row = jax.random.randint(k_frow, (self.n_food,), 0, g)
        food_col = self._off_wall(jax.random.randint(k_fcol, (self.n_food,), 0, g))
        food = jnp.zeros((g, g), bool).at[food_row, food_col].set(True)
        state = MultiRoomState(
            pos=jnp.stack([start_row, jnp.zeros((), jnp.int32)]).astype(jnp.int32),
            door_row=door_row.astype(jnp.int32),
            door_open=jnp.zeros((_MAX_WALLS,), bool),
            key_taken=jnp.zeros((_MAX_WALLS,), bool),
            key_pos=key_pos,
            food=food,
            goal=jnp.stack([goal_row, jnp.full((), g - 1)]).astype(jnp.int32),
            t=jnp.zeros((), jnp.int32),
            key=k_carry,
            level=jnp.full((), self.level, jnp.float32),
        )
        return state, self.observe(state)

    def observe(self, state: MultiRoomState) -> Obs:
        g = self.grid
        n_walls = self._n_walls(state.level)
        rows = jnp.arange(g)
        cols = jnp.arange(g)
        img = jnp.zeros((g, g, 3), jnp.uint8)
        # walls + doors (active walls only; inactive walls are floor)
        for w, c in enumerate(self.wall_cols):
            active = w < n_walls
            is_door = rows == state.door_row[w]
            col_rgb = jnp.where(
                is_door[:, None],
                jnp.where(state.door_open[w], jnp.asarray(_OPEN_RGB), jnp.asarray(_DOOR_RGB)),
                jnp.asarray(_WALL_RGB),
            )
            img = img.at[:, c, :].set(jnp.where(active, col_rgb, img[:, c, :]))
        # food, then keys (untaken, active walls), then goal, agent on top
        img = jnp.where(state.food[..., None], jnp.asarray(_FOOD_RGB), img)
        for w in range(_MAX_WALLS):
            kmask = (rows[:, None] == state.key_pos[w, 0]) & (cols[None, :] == state.key_pos[w, 1])
            kmask = kmask & (w < n_walls) & ~state.key_taken[w]
            img = jnp.where(kmask[..., None], jnp.asarray(_KEY_RGB), img)
        gmask = (rows[:, None] == state.goal[0]) & (cols[None, :] == state.goal[1])
        img = jnp.where(gmask[..., None], jnp.asarray(_GOAL_RGB), img)
        amask = (rows[:, None] == state.pos[0]) & (cols[None, :] == state.pos[1])
        img = jnp.where(amask[..., None], jnp.asarray(_AGENT_RGB), img)
        img = jnp.repeat(jnp.repeat(img, self.cell, axis=0), self.cell, axis=1)
        return {"rgb": img}

    def step(self, state: MultiRoomState, action: jax.Array):
        g = self.grid
        n_walls = self._n_walls(state.level)
        move = jnp.asarray(_MOVES)[action.astype(jnp.int32) % 5]
        cand = jnp.clip(state.pos + move, 0, g - 1)
        # collision: an active wall cell blocks unless it is that wall's
        # door AND the door is open
        blocked = jnp.zeros((), bool)
        for w, c in enumerate(self.wall_cols):
            at_wall = cand[1] == c
            passable = (cand[0] == state.door_row[w]) & state.door_open[w]
            blocked = blocked | ((w < n_walls) & at_wall & ~passable)
        pos = jnp.where(blocked, state.pos, cand)

        # key pickups unlock the matching door
        reward = jnp.float32(0.0)
        key_taken = state.key_taken
        door_open = state.door_open
        for w in range(_MAX_WALLS):
            on_key = (
                (pos[0] == state.key_pos[w, 0])
                & (pos[1] == state.key_pos[w, 1])
                & (w < n_walls)
                & ~key_taken[w]
            )
            reward = reward + 0.2 * on_key.astype(jnp.float32)
            key_taken = key_taken.at[w].set(key_taken[w] | on_key)
            door_open = door_open.at[w].set(door_open[w] | on_key)

        ate = state.food[pos[0], pos[1]]
        food = state.food.at[pos[0], pos[1]].set(False)
        reward = reward + 0.1 * ate.astype(jnp.float32)

        at_goal = (pos[0] == state.goal[0]) & (pos[1] == state.goal[1])
        reward = reward + at_goal.astype(jnp.float32)

        t = state.t + 1
        new_state = MultiRoomState(
            pos=pos,
            door_row=state.door_row,
            door_open=door_open,
            key_taken=key_taken,
            key_pos=state.key_pos,
            food=food,
            goal=state.goal,
            t=t,
            key=state.key,
            level=state.level,
        )
        terminated = at_goal
        truncated = jnp.logical_and(t >= self.max_episode_steps, jnp.logical_not(terminated))
        return new_state, self.observe(new_state), reward, terminated, truncated
