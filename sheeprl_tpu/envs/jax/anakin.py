"""Anakin fused rollouts: the environment inside the compiled update.

Podracer's Anakin architecture (arXiv:2104.06272) co-locates env stepping
and learning on the chip: a ``lax.scan`` over the batched pure-env step +
policy inference produces the whole rollout as device arrays, which the
algo's existing train phase consumes in the SAME ``fabric.compile``
executable.  Per update there is ONE dispatch and ZERO host↔device data
motion — no Python env workers, no observation shipping, no rollout
staging.  This is the structural answer to the BENCH_TPU.md honest
negative (classic-control PPO/SAC ran slower on-chip than on host: the
chip idled while ``AsyncVectorEnv`` stepped CPU gym processes).

The pieces:

* :func:`make_rollout_fn` — builds the jit-traceable rollout half:
  ``rollout(params, actor, key) -> (actor', rollout, last_obs, stats)``.
  ``actor`` is the persistent device-resident carry (batched ``EnvState``
  + episode accounting + the update counter), donated into each fused
  dispatch so env state lives in HBM across the whole run.
* :func:`init_actor_state` — resets the vector env and stages the carry
  onto the mesh: env-state leaves shard over the ``data`` axis along the
  env dimension (the ``fabric.shard_batch`` layout the train phase's
  minibatch gathers expect), exactly like the PR 9 replay ring.
* :func:`traced_polynomial_decay` — the in-trace twin of
  ``utils.polynomial_decay`` so annealed coefficients (clip/entropy/lr)
  are computed ON DEVICE from the donated update counter: a steady state
  under ``jax.transfer_guard_host_to_device("disallow")`` performs zero
  H2D transfers, explicit or implicit.

Rollout semantics match the host loops: SAME_STEP auto-reset (via
:class:`~sheeprl_tpu.envs.jax.core.VectorJaxEnv`), truncation bootstrap
``r += γ·V(final_obs)`` on truncated rows with the current params, dones =
terminated | truncated, observations stored pre-normalized (uint8 images →
float32/255) in the layout the train phases already consume.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import VectorJaxEnv


def traced_polynomial_decay(
    step: jax.Array, *, initial: float, final: float = 0.0, max_decay_steps: int = 100, power: float = 1.0
) -> jax.Array:
    """In-trace twin of ``utils.utils.polynomial_decay`` over a device step
    counter (clamped past ``max_decay_steps``, like the host version)."""
    frac = jnp.clip(1.0 - step.astype(jnp.float32) / float(max_decay_steps), 0.0, 1.0) ** power
    return jnp.float32((initial - final)) * frac + jnp.float32(final)


def prep_obs_fn(cnn_keys: Sequence[str], mlp_keys: Sequence[str]) -> Callable:
    """Device-side observation normalization: the traced twin of
    ``ppo.utils.obs_to_np`` (uint8 images → float32/255, vectors →
    float32).  Jax envs don't frame-stack, so no merge branch."""

    def prep(obs: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        out = {}
        for k in cnn_keys:
            out[k] = obs[k].astype(jnp.float32) / 255.0
        for k in mlp_keys:
            out[k] = obs[k].astype(jnp.float32)
        return out

    return prep


def env_actions_fn(action_space: gym.Space) -> Callable:
    """Traced twin of ``ppo.utils.actions_for_env``: stored float actions →
    what the env step consumes."""
    if isinstance(action_space, gym.spaces.Discrete):
        return lambda a: a[..., 0].astype(jnp.int32)
    if isinstance(action_space, gym.spaces.MultiDiscrete):
        return lambda a: a.astype(jnp.int32)
    low = np.asarray(action_space.low, np.float32)
    high = np.asarray(action_space.high, np.float32)
    return lambda a: jnp.clip(a.astype(jnp.float32), low, high)


def init_actor_state(
    fabric: Any,
    venv: VectorJaxEnv,
    key: jax.Array,
    start_update: int,
    sharded: bool,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Reset the batched env and stage the persistent actor carry onto the
    mesh: env-dimension leaves shard over ``data`` via the sharding
    engine's env-state spec (``parallel/sharding.env_state_sharding`` —
    the replay-ring placement, one axis earlier) when the env count
    divides the data degree, else replicate.  ``extra`` adds further
    env-leading-axis carry leaves under the same placement law (the
    recurrent loop's LSTM state / prev-action encoding / episode-start
    mask)."""
    from sheeprl_tpu.parallel.sharding import env_state_sharding

    env_state, _ = venv.reset(key)
    actor = {
        "env": env_state,
        "ep_ret": jnp.zeros((venv.num_envs,), jnp.float32),
        "ep_len": jnp.zeros((venv.num_envs,), jnp.int32),
        **(extra or {}),
    }
    placement = (
        env_state_sharding(fabric.mesh, venv.num_envs, fabric.data_axis)
        if sharded
        else fabric.replicated
    )
    actor = jax.device_put(actor, placement)
    actor["update"] = fabric.replicate(jnp.asarray(start_update, jnp.int32))
    return actor


def make_rollout_fn(
    venv: VectorJaxEnv,
    agent_apply: Callable,
    sample_fn: Callable,
    *,
    cnn_keys: Sequence[str],
    mlp_keys: Sequence[str],
    action_space: gym.Space,
    gamma: float,
    rollout_steps: int,
    store_logprobs: bool = True,
) -> Callable:
    """Build ``rollout(p, actor, key) -> (actor', rollout, last_obs, stats)``.

    ``rollout`` leaves are ``(T, B, *feat)`` in the exact layout the
    on-policy train phases consume (obs pre-normalized, actions in storage
    float layout, rewards truncation-bootstrapped, dones float).  ``stats``
    carries per-step ``(T, B)`` episode-completion arrays — small, pulled
    D2H by the loop for logging (legal under the H2D-scoped guard).
    """
    prep = prep_obs_fn(cnn_keys, mlp_keys)
    to_env = env_actions_fn(action_space)
    obs_keys = tuple(cnn_keys) + tuple(mlp_keys)

    def rollout(p: Any, actor: Dict[str, Any], key: jax.Array):
        def body(carry, k_step):
            env_state, ep_ret, ep_len = carry
            pobs = prep(venv.observe(env_state))
            out, value = agent_apply(p, pobs)
            actions, logprob, _ = sample_fn(out, k_step)
            env_state, _, reward, term, trunc, final_obs = venv.step(env_state, to_env(actions))
            # truncation bootstrap with the CURRENT params (the host loops'
            # `rewards[truncated] += gamma * V(final_obs)` — here final_obs
            # is always available, no padded re-dispatch needed)
            _, v_final = agent_apply(p, prep(final_obs))
            trunc_f = trunc.astype(jnp.float32)
            boot_reward = reward + gamma * v_final[..., 0] * trunc_f
            done = jnp.logical_or(term, trunc)
            done_f = done.astype(jnp.float32)
            ep_ret = ep_ret + reward
            ep_len = ep_len + 1
            step_out = {
                **{k: pobs[k] for k in obs_keys},
                "actions": actions,
                "logprobs": logprob,
                "rewards": boot_reward,
                "dones": done_f,
                "ep_done": done,
                "ep_ret": ep_ret,
                "ep_len": ep_len,
            }
            ep_ret = ep_ret * (1.0 - done_f)
            ep_len = ep_len * (1 - done.astype(jnp.int32))
            return (env_state, ep_ret, ep_len), step_out

        keys = jax.random.split(key, rollout_steps)
        (env_state, ep_ret, ep_len), traj = jax.lax.scan(
            body, (actor["env"], actor["ep_ret"], actor["ep_len"]), keys
        )
        stats = {k: traj.pop(k) for k in ("ep_done", "ep_ret", "ep_len")}
        if not store_logprobs:
            traj.pop("logprobs")
        last_obs = prep(venv.observe(env_state))
        new_actor = {
            "env": env_state,
            "ep_ret": ep_ret,
            "ep_len": ep_len,
            "update": actor["update"] + 1,
        }
        return new_actor, traj, last_obs, stats

    return rollout


def make_recurrent_rollout_fn(
    venv: VectorJaxEnv,
    step_apply: Callable,
    sample_fn: Callable,
    encode_prev_actions: Callable,
    *,
    mlp_keys: Sequence[str],
    action_space: gym.Space,
    gamma: float,
    rollout_steps: int,
) -> Callable:
    """The recurrent (LSTM) twin of :func:`make_rollout_fn` for
    ``ppo_recurrent`` (ROADMAP item 5's remaining half): the ``nn.scan``
    policy's per-step method runs INSIDE the fused ``lax.scan`` rollout,
    with the recurrent state, previous-action encoding and episode-start
    mask all living in the donated device-resident actor carry.

    ``step_apply(p, carry, obs, prev_actions, is_first) -> (carry',
    (actor_out, value))`` is the agent's single-step apply;
    ``encode_prev_actions(actions)`` is the next-step action encoding
    (one-hot per discrete branch).  Returns ``rollout(p, actor, key) ->
    (actor', rollout, init_carry, last_values, stats)`` where ``rollout``
    carries the extra ``prev_actions``/``is_first`` sequences the
    recurrent train phase consumes, ``init_carry`` is the recurrent state
    at the segment start and ``last_values`` the bootstrap values after
    the last step — everything the existing ``ppo_recurrent`` train phase
    takes, computed without a single host↔device transfer.

    Truncation bootstrap uses the POST-step recurrent state on the true
    final observation (the host loop's padded re-dispatch, in-trace).
    """
    prep = prep_obs_fn((), mlp_keys)
    to_env = env_actions_fn(action_space)
    num_envs = venv.num_envs

    def rollout(p: Any, actor: Dict[str, Any], key: jax.Array):
        init_carry = actor["carry"]

        def body(carry, k_step):
            env_state, (c, h), prev_actions, is_first, ep_ret, ep_len = carry
            pobs = prep(venv.observe(env_state))
            (c2, h2), (actor_out, value) = step_apply(p, (c, h), pobs, prev_actions, is_first)
            actions, logprob = sample_fn(actor_out, k_step)
            env_state, _, reward, term, trunc, final_obs = venv.step(env_state, to_env(actions))
            prev_a_next = encode_prev_actions(actions)
            # truncation bootstrap with the post-step recurrent state
            _, (_, v_final) = step_apply(
                p, (c2, h2), prep(final_obs), prev_a_next,
                jnp.zeros((num_envs, 1), jnp.float32),
            )
            trunc_f = trunc.astype(jnp.float32)
            boot_reward = reward + gamma * v_final[..., 0] * trunc_f
            done = jnp.logical_or(term, trunc)
            done_f = done.astype(jnp.float32)
            ep_ret = ep_ret + reward
            ep_len = ep_len + 1
            step_out = {
                **pobs,
                "actions": actions,
                "logprobs": logprob,
                "rewards": boot_reward,
                "dones": done_f,
                "is_first": is_first,
                "prev_actions": prev_actions,
                "ep_done": done,
                "ep_ret": ep_ret,
                "ep_len": ep_len,
            }
            ep_ret = ep_ret * (1.0 - done_f)
            ep_len = ep_len * (1 - done.astype(jnp.int32))
            # episode boundary resets the next step's recurrent inputs
            prev_a_next = prev_a_next * (1.0 - done_f[..., None])
            is_first_next = done_f[..., None]
            return (env_state, (c2, h2), prev_a_next, is_first_next, ep_ret, ep_len), step_out

        keys = jax.random.split(key, rollout_steps)
        (env_state, carry2, prev_actions, is_first, ep_ret, ep_len), traj = jax.lax.scan(
            body,
            (
                actor["env"], actor["carry"], actor["prev_actions"],
                actor["is_first"], actor["ep_ret"], actor["ep_len"],
            ),
            keys,
        )
        stats = {k: traj.pop(k) for k in ("ep_done", "ep_ret", "ep_len")}
        # bootstrap values for the post-rollout state, with the live carry
        _, (_, last_v) = step_apply(
            p, carry2, prep(venv.observe(env_state)), prev_actions, is_first
        )
        new_actor = {
            "env": env_state,
            "carry": carry2,
            "prev_actions": prev_actions,
            "is_first": is_first,
            "ep_ret": ep_ret,
            "ep_len": ep_len,
            "update": actor["update"] + 1,
        }
        return new_actor, traj, init_carry, last_v[..., 0], stats

    return rollout


def episode_stats_from_device(stats: Dict[str, jax.Array]) -> Tuple[np.ndarray, np.ndarray]:
    """Pull the per-step completion arrays D2H and flatten to the finished
    episodes' ``(returns, lengths)`` — the fused path's counterpart of
    ``utils.env.episode_stats``."""
    done = np.asarray(stats["ep_done"]).reshape(-1)
    rets = np.asarray(stats["ep_ret"]).reshape(-1)[done]
    lens = np.asarray(stats["ep_len"]).reshape(-1)[done]
    return rets, lens
