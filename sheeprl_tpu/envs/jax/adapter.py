"""``JaxToGymAdapter`` — run any pure-JAX env through the gymnasium API.

This is the compatibility half of the ``envs/jax`` design: every EXISTING
algo loop (on- and off-policy, coupled and decoupled) can select
``env=jax_*`` and run unmodified — the adapter slots into ``make_env``'s
wrapper pipeline like any other suite, and the vector wrappers
(``SyncVectorEnv``/``AsyncVectorEnv`` with SAME_STEP autoreset) provide
``final_obs``/``final_info`` exactly as for CPU gym envs.

Seeding follows the gymnasium contract: ``reset(seed=s)`` derives the env's
JAX PRNG stream from ``s`` (reproducible trajectories per seed); unseeded
resets continue the stream.  The per-step ``step``/``reset`` programs are
jitted once (tiny, shape-stable).

The jax_* env groups default to ``sync_env: true``: stepping one JAX
program per env instance inside forked ``AsyncVectorEnv`` workers would
re-initialize a JAX runtime per worker for envs that are *cheaper than the
IPC round-trip* — and the real speed path is the fused Anakin rollout, not
the adapter.  The adapter exists for correctness/compatibility, and the
scenario matrix runs it on every algo family.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv


class JaxToGymAdapter(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(self, env: JaxEnv, seed: Optional[int] = None):
        self._env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self._step_fn = jax.jit(env.step)
        self._reset_fn = jax.jit(env.reset)
        self._state: Any = None
        self._key: Optional[jax.Array] = None
        if seed is not None:
            self._key = jax.random.PRNGKey(int(seed))

    def _next_key(self) -> jax.Array:
        if self._key is None:
            # no seed ever provided: draw one from gymnasium's np_random so
            # the standard `env.reset(seed=...)` machinery governs it
            self._key = jax.random.PRNGKey(int(self.np_random.integers(2**31 - 1)))
        self._key, k = jax.random.split(self._key)
        return k

    def _host_obs(self, obs: Dict[str, jax.Array]) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in obs.items()}

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        super().reset(seed=seed)
        if seed is not None:
            self._key = jax.random.PRNGKey(int(seed))
        self._state, obs = self._reset_fn(self._next_key())
        return self._host_obs(obs), {}

    def step(self, action: Any):
        action = np.asarray(action)
        self._state, obs, reward, terminated, truncated = self._step_fn(self._state, action)
        return (
            self._host_obs(obs),
            float(reward),
            bool(terminated),
            bool(truncated),
            {},
        )

    def render(self) -> Optional[np.ndarray]:
        if self._state is not None and "rgb" in self.observation_space.spaces:
            return np.asarray(self._env.observe(self._state)["rgb"])
        return None
