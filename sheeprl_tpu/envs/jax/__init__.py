"""Pure-JAX vectorized environments + Anakin fused rollouts.

See docs/jax_envs.md for the env authoring contract, the adapter path, and
the fused-rollout design.
"""

from sheeprl_tpu.envs.jax.core import JaxEnv, VectorJaxEnv
from sheeprl_tpu.envs.jax.registry import (
    JAX_ENVS,
    anakin_enabled,
    is_jax_native,
    jax_env_from_cfg,
    make_jax_env,
)

__all__ = [
    "JaxEnv",
    "VectorJaxEnv",
    "JAX_ENVS",
    "anakin_enabled",
    "is_jax_native",
    "jax_env_from_cfg",
    "make_jax_env",
]
