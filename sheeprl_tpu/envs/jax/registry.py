"""Registry + config plumbing for the pure-JAX env family.

``env=jax_*`` Hydra groups set ``env.wrapper.kind: jax`` plus a registry
``id``; :func:`jax_env_from_cfg` builds the env from there.  Two consumers:

* the :class:`~sheeprl_tpu.envs.jax.adapter.JaxToGymAdapter` path
  (``utils/env.py``), which lets EVERY existing algo loop run these envs
  unmodified through the current vector-env machinery, and
* the Anakin fused-rollout path (``envs/jax/anakin.py``), which the
  on-policy loops (ppo, a2c) select via :func:`anakin_enabled` to step the
  batched env INSIDE the compiled update.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from sheeprl_tpu.envs.jax.core import JaxEnv

JAX_ENVS: Dict[str, Callable[..., JaxEnv]] = {}


def _register(name: str):
    def deco(builder: Callable[..., JaxEnv]):
        JAX_ENVS[name] = builder
        return builder

    return deco


@_register("cartpole")
def _cartpole(**kwargs: Any) -> JaxEnv:
    from sheeprl_tpu.envs.jax.cartpole import JaxCartPole

    return JaxCartPole(**kwargs)


@_register("pendulum")
def _pendulum(**kwargs: Any) -> JaxEnv:
    from sheeprl_tpu.envs.jax.pendulum import JaxPendulum

    return JaxPendulum(**kwargs)


@_register("forage")
def _forage(**kwargs: Any) -> JaxEnv:
    from sheeprl_tpu.envs.jax.forage import JaxForage

    return JaxForage(**kwargs)


@_register("multiroom")
def _multiroom(**kwargs: Any) -> JaxEnv:
    from sheeprl_tpu.envs.jax.multiroom import JaxMultiRoom

    return JaxMultiRoom(**kwargs)


def make_jax_env(env_id: str, **kwargs: Any) -> JaxEnv:
    """Build a registered pure-JAX env; accepts both the bare registry name
    (``cartpole``) and the config-group spelling (``jax_cartpole``)."""
    name = env_id[4:] if env_id.startswith("jax_") else env_id
    if name not in JAX_ENVS:
        raise ValueError(f"Unknown jax env '{env_id}'; options: {sorted(JAX_ENVS)}")
    return JAX_ENVS[name](**kwargs)


def is_jax_native(cfg: Any) -> bool:
    """True when the selected env group is a pure-JAX env (wrapper kind)."""
    wrapper = cfg.env.get("wrapper") or {}
    return isinstance(wrapper, dict) and wrapper.get("kind") == "jax"


def jax_env_from_cfg(cfg: Any) -> JaxEnv:
    """Build the configured jax env (wrapper kwargs pass through to the
    registered constructor, like every other suite wrapper)."""
    wrapper = dict(cfg.env.get("wrapper") or {})
    env_id = wrapper.pop("id", None) or cfg.env.id
    wrapper.pop("kind", None)
    # difficulty axis (docs/jax_envs.md): a top-level env.level override
    # reaches every jax env ctor without per-env wrapper plumbing
    if cfg.env.get("level") is not None:
        wrapper.setdefault("level", float(cfg.env.level))
    env = make_jax_env(env_id, **wrapper)
    if cfg.env.get("max_episode_steps"):
        env.max_episode_steps = int(cfg.env.max_episode_steps)
    return env


def anakin_enabled(cfg: Any, fabric: Any) -> bool:
    """Whether an on-policy loop should fuse its rollout (Anakin mode).

    ``algo.anakin``: ``auto`` (default) fuses whenever the env is
    jax-native and the run is single-process; ``True`` demands it (raising
    on a non-jax env); ``False`` forces the adapter/vector-env path even
    for jax envs (useful for A/B benches and the scenario matrix).
    Multi-process runs fall back to the adapter path: the fused program is
    a per-process dispatch and the cross-host rollout-pool semantics of
    the decoupled samplers don't apply to it yet.
    """
    mode = cfg.algo.get("anakin", "auto")
    native = is_jax_native(cfg)
    if isinstance(mode, str) and mode.lower() == "auto":
        wanted = native
    elif bool(mode):
        if not native:
            raise ValueError(
                "algo.anakin=True requires a pure-JAX env (env=jax_*); "
                f"got env.id={cfg.env.id!r}"
            )
        wanted = True
    else:
        return False
    if wanted and fabric.num_processes > 1:
        from sheeprl_tpu.parallel.distributed import rank_zero_warn

        # once, on rank 0 — N processes each printing the same fallback
        # turns a pod launch into a wall of duplicate warnings
        rank_zero_warn(
            "algo.anakin: multi-process run — falling back to the vector-env "
            "adapter path (fused rollouts are single-process for now)",
            key="anakin.multiprocess_fallback",
        )
        return False
    return wanted
