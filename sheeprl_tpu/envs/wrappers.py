"""Generic environment wrappers.

Re-implementations (gymnasium 1.x API) of the reference's wrapper set
(reference: sheeprl/envs/wrappers.py:13-342).  One intentional difference for
the TPU build: image observations are channel-LAST ``(H, W, C)`` throughout —
the layout XLA's TPU convolutions prefer — where the reference standardizes
on torch's ``(C, H, W)``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Sequence, SupportsFloat, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Zero out velocity components of classic-control observations, turning
    them into partially-observable tasks (reference: envs/wrappers.py:13-45)."""

    velocity_indices: Dict[str, np.ndarray] = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLander-v3": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v3": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        env_id = env.spec.id if env.spec is not None else ""
        if env_id not in self.velocity_indices:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}")
        self.mask = np.ones(env.observation_space.shape, dtype=np.float32)
        self.mask[self.velocity_indices[env_id]] = 0.0

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat each action ``amount`` times, summing rewards
    (reference: envs/wrappers.py:48-71)."""

    def __init__(self, env: gym.Env, amount: int):
        super().__init__(env)
        if amount <= 0:
            raise ValueError(f"action_repeat must be positive, got {amount}")
        self._amount = int(amount)

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        total_reward = 0.0
        obs, terminated, truncated, info = None, False, False, {}
        for _ in range(self._amount):
            obs, reward, terminated, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if terminated or truncated:
                break
        return obs, total_reward, terminated, truncated, info


class RestartOnException(gym.Wrapper):
    """Recreate a crashed environment instead of killing training
    (reference: envs/wrappers.py:74-123).  At most ``max_restarts`` within
    ``window`` seconds; beyond that the exception propagates.  After a
    restart, ``info["restart_on_exception"]`` is set so the train loop can
    patch its replay buffer (as DreamerV3 does,
    reference: sheeprl/algos/dreamer_v3/dreamer_v3.py:595-608).
    """

    def __init__(self, env_fn: Callable[[], gym.Env], max_restarts: int = 5, window: float = 60.0):
        self._env_fn = env_fn
        self._max_restarts = max_restarts
        self._window = window
        self._restart_times: deque = deque()
        super().__init__(env_fn())

    def _restart(self) -> None:
        now = time.monotonic()
        while self._restart_times and now - self._restart_times[0] > self._window:
            self._restart_times.popleft()
        if len(self._restart_times) >= self._max_restarts:
            raise RuntimeError(
                f"Environment crashed {len(self._restart_times)} times within "
                f"{self._window}s; giving up"
            )
        self._restart_times.append(now)
        try:
            self.env.close()
        except Exception:
            pass
        self.env = self._env_fn()

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        try:
            return self.env.step(action)
        except Exception:
            self._restart()
            obs, info = self.env.reset()
            info = dict(info)
            info["restart_on_exception"] = True
            # NOT terminal (reference: envs/wrappers.py:87-103): reporting a
            # done here would trigger a second autoreset and bury this info
            # under final_info — the train loop patches its replay buffer
            # from the top-level flag instead
            return obs, 0.0, False, False, info

    def reset(self, **kwargs: Any) -> Tuple[Any, Dict[str, Any]]:
        try:
            return self.env.reset(**kwargs)
        except Exception:
            self._restart()
            obs, info = self.env.reset(**kwargs)
            info = dict(info)
            info["restart_on_exception"] = True
            return obs, info


class FrameStack(gym.Wrapper):
    """Stack the last ``num_stack`` frames of every image key of a Dict
    observation space, with optional temporal ``dilation``
    (reference: envs/wrappers.py:126-182).

    Stacking adds a leading axis: ``(H, W, C)`` → ``(num_stack, H, W, C)``.
    """

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"num_stack must be positive, got {num_stack}")
        if not isinstance(env.observation_space, spaces.Dict):
            raise RuntimeError("FrameStack requires a Dict observation space")
        self._num_stack = int(num_stack)
        self._dilation = int(dilation)
        self._cnn_keys = [
            k for k in cnn_keys if len(env.observation_space[k].shape) == 3
        ]
        if not self._cnn_keys:
            raise RuntimeError(f"No image keys to stack among {list(cnn_keys)}")
        self._frames: Dict[str, deque] = {
            k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys
        }
        new_spaces = dict(env.observation_space.spaces)
        for k in self._cnn_keys:
            sp = env.observation_space[k]
            new_spaces[k] = spaces.Box(
                np.repeat(sp.low[None], num_stack, axis=0),
                np.repeat(sp.high[None], num_stack, axis=0),
                (num_stack, *sp.shape),
                sp.dtype,
            )
        self.observation_space = spaces.Dict(new_spaces)

    def _stacked(self, key: str) -> np.ndarray:
        frames = list(self._frames[key])[:: -self._dilation][::-1]
        return np.stack(frames, axis=0)

    def _observation(self, obs: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(obs)
        for k in self._cnn_keys:
            out[k] = self._stacked(k)
        return out

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        obs, reward, terminated, truncated, info = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
        return self._observation(obs), reward, terminated, truncated, info

    def reset(self, **kwargs: Any) -> Tuple[Any, Dict[str, Any]]:
        obs, info = self.env.reset(**kwargs)
        for k in self._cnn_keys:
            for _ in range(self._num_stack * self._dilation):
                self._frames[k].append(obs[k])
        return self._observation(obs), info


class RewardAsObservationWrapper(gym.Wrapper):
    """Expose the last reward as an extra observation key
    (reference: envs/wrappers.py:185-241)."""

    def __init__(self, env: gym.Env):
        super().__init__(env)
        reward_space = spaces.Box(-np.inf, np.inf, (1,), np.float32)
        if isinstance(env.observation_space, spaces.Dict):
            new_spaces = {**env.observation_space.spaces, "reward": reward_space}
        else:
            new_spaces = {"obs": env.observation_space, "reward": reward_space}
        self.observation_space = spaces.Dict(new_spaces)

    def _wrap(self, obs: Any, reward: float) -> Dict[str, Any]:
        r = np.array([reward], dtype=np.float32)
        if isinstance(obs, dict):
            return {**obs, "reward": r}
        return {"obs": obs, "reward": r}

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._wrap(obs, float(reward)), reward, terminated, truncated, info

    def reset(self, **kwargs: Any) -> Tuple[Any, Dict[str, Any]]:
        obs, info = self.env.reset(**kwargs)
        return self._wrap(obs, 0.0), info


class ActionsAsObservationWrapper(gym.Wrapper):
    """Expose the last ``num_stack`` actions as an observation key
    (reference: envs/wrappers.py:258-342).

    Discrete actions are one-hot encoded; multi-discrete become concatenated
    one-hots; continuous are used as-is.  ``noop`` defines the action used to
    fill the stack on reset.  ``dilation`` skips intermediate actions.
    """

    def __init__(self, env: gym.Env, num_stack: int, noop: Any, dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"num_stack must be positive, got {num_stack}")
        if dilation <= 0:
            raise ValueError(f"dilation must be positive, got {dilation}")
        self._num_stack = num_stack
        self._dilation = dilation
        act_space = env.action_space
        if isinstance(act_space, spaces.Discrete):
            self._per_action = int(act_space.n)
        elif isinstance(act_space, spaces.MultiDiscrete):
            self._per_action = int(np.sum(act_space.nvec))
        elif isinstance(act_space, spaces.Box):
            self._per_action = int(np.prod(act_space.shape))
        else:
            raise RuntimeError(f"Unsupported action space {type(act_space)}")
        self._noop = noop
        self._actions: deque = deque(maxlen=num_stack * dilation)
        action_obs_space = spaces.Box(-np.inf, np.inf, (num_stack * self._per_action,), np.float32)
        if isinstance(env.observation_space, spaces.Dict):
            new_spaces = {**env.observation_space.spaces, "action_stack": action_obs_space}
        else:
            new_spaces = {"obs": env.observation_space, "action_stack": action_obs_space}
        self.observation_space = spaces.Dict(new_spaces)

    def _encode(self, action: Any) -> np.ndarray:
        act_space = self.env.action_space
        if isinstance(act_space, spaces.Discrete):
            out = np.zeros(self._per_action, dtype=np.float32)
            out[int(np.asarray(action).reshape(()))] = 1.0
            return out
        if isinstance(act_space, spaces.MultiDiscrete):
            parts = []
            for a, n in zip(np.asarray(action).flatten(), act_space.nvec):
                oh = np.zeros(int(n), dtype=np.float32)
                oh[int(a)] = 1.0
                parts.append(oh)
            return np.concatenate(parts)
        return np.asarray(action, dtype=np.float32).flatten()

    def _obs_with_actions(self, obs: Any) -> Dict[str, Any]:
        actions = list(self._actions)[:: -self._dilation][::-1]
        stack = np.concatenate([self._encode(a) for a in actions])
        if isinstance(obs, dict):
            return {**obs, "action_stack": stack}
        return {"obs": obs, "action_stack": stack}

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        self._actions.append(action)
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._obs_with_actions(obs), reward, terminated, truncated, info

    def reset(self, **kwargs: Any) -> Tuple[Any, Dict[str, Any]]:
        obs, info = self.env.reset(**kwargs)
        for _ in range(self._num_stack * self._dilation):
            self._actions.append(self._noop)
        return self._obs_with_actions(obs), info


class FaultInjectionEnv(gym.Wrapper):
    """Fire the resilience engine's ``env.step`` / ``env.reset`` injection
    sites (``sheeprl_tpu.resilience.faults``) around the wrapped env.

    Only applied by ``utils.env.make_env`` when an active fault plan targets
    an ``env.*`` site, so the disabled path adds no wrapper at all.  It sits
    INSIDE :class:`RestartOnException` (injected crashes exercise the real
    restart path) and inside the vector worker (injected hangs exercise the
    vector-level step-deadline watchdog).
    """

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        from sheeprl_tpu.resilience.faults import fault_point

        fault_point("env.step")
        return self.env.step(action)

    def reset(self, **kwargs: Any) -> Tuple[Any, Dict[str, Any]]:
        from sheeprl_tpu.resilience.faults import fault_point

        fault_point("env.reset")
        return self.env.reset(**kwargs)


class GrayscaleRenderWrapper(gym.Wrapper):
    """Make ``render()`` return 3-channel frames for video capture even when
    observations are grayscale (reference: envs/wrappers.py:244-255)."""

    def render(self) -> Any:
        frame = self.env.render()
        if frame is not None:
            frame = np.asarray(frame)
            if frame.ndim == 2:
                frame = np.repeat(frame[..., None], 3, axis=-1)
            elif frame.ndim == 3 and frame.shape[-1] == 1:
                frame = np.repeat(frame, 3, axis=-1)
        return frame
