"""Gated compiler from :class:`MineRLTaskSpec` records to minerl EnvSpecs.

Role parity with the reference's imperative spec subclasses (reference:
sheeprl/envs/minerl_envs/backend.py:19-61): base observables (POV, location,
life stats), the simple keyboard+camera action set, and the break-speed
server handler.  The design differs deliberately: task content lives in the
declarative records of :mod:`sheeprl_tpu.envs.minerl_envs.specs` (testable
without minerl) and this module compiles a record into a concrete
``EnvSpec`` subclass when the ``minerl`` package is installed.
"""

from __future__ import annotations

from typing import Any, List

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE
from sheeprl_tpu.envs.minerl_envs.specs import (
    NONE,
    OTHER,
    SIMPLE_KEYBOARD_ACTIONS,
    MineRLTaskSpec,
    success_from_rewards,
)

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError(
        "The MineRL spec builders need the 'minerl' package (plus a JDK); "
        "it is not available in this image. The task definitions themselves "
        "live in sheeprl_tpu/envs/minerl_envs/specs.py and do not need it."
    )

from minerl.herobraine.env_spec import EnvSpec  # type: ignore  # noqa: E402
from minerl.herobraine.hero import handler, handlers  # type: ignore  # noqa: E402
from minerl.herobraine.hero.mc import INVERSE_KEYMAP  # type: ignore  # noqa: E402


class BreakSpeedMultiplier(handler.Handler):
    """Server-side block-breaking speed-up (the 'fast mining' used by the
    Dreamer Minecraft experiments)."""

    def __init__(self, multiplier: float = 1.0):
        self.multiplier = multiplier

    def to_string(self) -> str:
        return f"break_speed({self.multiplier})"

    def xml_template(self) -> str:
        return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"


def compile_spec(
    spec: MineRLTaskSpec,
    resolution=(64, 64),
    break_speed: int = 100,
    **env_spec_kwargs: Any,
) -> EnvSpec:
    """Build a concrete minerl ``EnvSpec`` from a declarative task record."""

    class _CompiledSpec(EnvSpec):
        def __init__(self) -> None:
            self.resolution = resolution
            self.break_speed = break_speed
            # Time limits are enforced by the framework's TimeLimit wrapper
            # (MineRL cannot distinguish terminated from truncated itself).
            super().__init__(spec.name, max_episode_steps=None, **env_spec_kwargs)

        # -- agent ---------------------------------------------------------
        def create_agent_start(self) -> List[handler.Handler]:
            start: List[handler.Handler] = [BreakSpeedMultiplier(self.break_speed)]
            if spec.start_inventory:
                start.append(
                    handlers.SimpleInventoryAgentStart(
                        [dict(type=item, quantity=qty) for item, qty in spec.start_inventory]
                    )
                )
            return start

        def create_observables(self) -> List[handler.Handler]:
            obs = [
                handlers.POVObservation(self.resolution),
                handlers.ObservationFromCurrentLocation(),
                handlers.ObservationFromLifeStats(),
                handlers.FlatInventoryObservation(list(spec.inventory_items)),
            ]
            if spec.compass:
                obs.append(handlers.CompassObservation(angle=True, distance=False))
            if spec.equipment_obs_items:
                obs.append(
                    handlers.EquippedItemObservation(
                        items=list(spec.equipment_obs_items), _default="air", _other=OTHER
                    )
                )
            return obs

        def create_actionables(self) -> List[handler.Handler]:
            acts: List[handler.Handler] = [
                handlers.KeybasedCommandAction(k, v)
                for k, v in INVERSE_KEYMAP.items()
                if k in SIMPLE_KEYBOARD_ACTIONS
            ] + [handlers.CameraAction()]
            enum_actions = (
                (handlers.PlaceBlock, spec.place_items),
                (handlers.EquipAction, spec.equip_items),
                (handlers.CraftAction, spec.craft_items),
                (handlers.CraftNearbyAction, spec.nearby_craft_items),
                (handlers.SmeltItemNearby, spec.nearby_smelt_items),
            )
            for handler_cls, vocab in enum_actions:
                if vocab:
                    acts.append(handler_cls(list(vocab), _other=NONE, _default=NONE))
            return acts

        def create_rewardables(self) -> List[handler.Handler]:
            rewards: List[handler.Handler] = []
            if spec.milestones:
                rewards.append(
                    handlers.RewardForCollectingItemsOnce(
                        [dict(type=i, amount=1, reward=r) for i, r in spec.milestones]
                    )
                )
            if spec.touch_block_rewards:
                rewards.append(
                    handlers.RewardForTouchingBlockType(
                        [
                            {"type": block, "behaviour": "onceOnly", "reward": r}
                            for block, r in spec.touch_block_rewards
                        ]
                    )
                )
            if spec.distance_reward_per_block is not None:
                rewards.append(
                    handlers.RewardForDistanceTraveledToCompassTarget(
                        reward_per_block=spec.distance_reward_per_block
                    )
                )
            return rewards

        def create_agent_handlers(self) -> List[handler.Handler]:
            out: List[handler.Handler] = []
            if spec.quit_on_touch:
                out.append(handlers.AgentQuitFromTouchingBlockType(list(spec.quit_on_touch)))
            if spec.quit_on_possess:
                out.append(
                    handlers.AgentQuitFromPossessingItem(
                        [dict(type=i, amount=a) for i, a in spec.quit_on_possess]
                    )
                )
            if spec.quit_on_craft:
                out.append(
                    handlers.AgentQuitFromCraftingItem(
                        [dict(type=i, amount=a) for i, a in spec.quit_on_craft]
                    )
                )
            return out

        def create_monitors(self) -> List[handler.Handler]:
            return []

        # -- server --------------------------------------------------------
        def create_server_world_generators(self) -> List[handler.Handler]:
            if spec.biome is not None:
                return [handlers.BiomeGenerator(biome=spec.biome, force_reset=True)]
            return [handlers.DefaultWorldGenerator(force_reset=True)]

        def create_server_quit_producers(self) -> List[handler.Handler]:
            return [handlers.ServerQuitWhenAnyAgentFinishes()]

        def create_server_decorators(self) -> List[handler.Handler]:
            if spec.compass:
                # navigate target: a diamond block ~64m out with a jittered
                # compass reading
                return [
                    handlers.NavigationDecorator(
                        max_randomized_radius=64,
                        min_randomized_radius=64,
                        block="diamond_block",
                        placement="surface",
                        max_radius=8,
                        min_radius=0,
                        max_randomized_distance=8,
                        min_randomized_distance=0,
                        randomize_compass_location=True,
                    )
                ]
            return []

        def create_server_initial_conditions(self) -> List[handler.Handler]:
            return [
                handlers.TimeInitialCondition(
                    allow_passage_of_time=spec.time_passes, start_time=6000
                ),
                *([handlers.WeatherInitialCondition("clear")] if not spec.time_passes else []),
                handlers.SpawningInitialCondition(
                    "true" if spec.allow_spawning else "false"
                ),
            ]

        # -- bookkeeping ---------------------------------------------------
        def is_from_folder(self, folder: str) -> bool:
            return False  # custom tasks have no demonstration dataset

        def get_docstring(self) -> str:
            return f"Custom task {spec.name} compiled from a declarative spec."

        def determine_success_from_rewards(self, rewards: list) -> bool:
            return success_from_rewards(spec, list(rewards))

    return _CompiledSpec()
