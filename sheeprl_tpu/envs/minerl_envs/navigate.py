"""Custom Navigate task (reference: sheeprl/envs/minerl_envs/navigate.py:18-97).

Thin gated entry point: the task content is the declarative
:func:`sheeprl_tpu.envs.minerl_envs.specs.navigate_spec` record; this module
compiles it into a minerl ``EnvSpec`` when the backend is installed.
"""

from __future__ import annotations

from typing import Any

from sheeprl_tpu.envs.minerl_envs.specs import navigate_spec

NAVIGATE_STEPS = 6000


class CustomNavigate:
    """Callable-spec facade matching the reference class's construction API:
    ``CustomNavigate(dense=..., extreme=..., break_speed=...).make()``."""

    def __init__(self, dense: bool = False, extreme: bool = False, break_speed: int = 100, **kwargs: Any):
        from sheeprl_tpu.envs.minerl_envs.backend import compile_spec  # gated import

        kwargs.pop("max_episode_steps", None)  # handled by the TimeLimit wrapper
        self._spec = compile_spec(
            navigate_spec(dense=dense, extreme=extreme), break_speed=break_speed, **kwargs
        )

    def make(self) -> Any:
        return self._spec.make()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._spec, name)
