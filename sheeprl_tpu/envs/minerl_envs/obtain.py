"""Custom Obtain tasks (reference: sheeprl/envs/minerl_envs/obtain.py:23-326).

Thin gated entry points: the item hierarchies, reward schedules, action
vocabularies and quit conditions are the declarative records in
:mod:`sheeprl_tpu.envs.minerl_envs.specs`; this module compiles them into
minerl ``EnvSpec`` objects when the backend is installed.
"""

from __future__ import annotations

from typing import Any, Callable

from sheeprl_tpu.envs.minerl_envs.specs import (
    MineRLTaskSpec,
    obtain_diamond_spec,
    obtain_iron_pickaxe_spec,
)


class _CustomObtain:
    def __init__(self, spec_factory: Callable[[bool], MineRLTaskSpec], dense: bool, break_speed: int, **kwargs: Any):
        from sheeprl_tpu.envs.minerl_envs.backend import compile_spec  # gated import

        kwargs.pop("max_episode_steps", None)  # handled by the TimeLimit wrapper
        kwargs.pop("extreme", None)  # navigate-only knob
        self._spec = compile_spec(spec_factory(dense), break_speed=break_speed, **kwargs)

    def make(self) -> Any:
        return self._spec.make()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._spec, name)


class CustomObtainDiamond(_CustomObtain):
    """18000-step (15 min) diamond hunt with the 12-milestone reward chain."""

    def __init__(self, dense: bool = False, break_speed: int = 100, **kwargs: Any):
        super().__init__(obtain_diamond_spec, dense, break_speed, **kwargs)


class CustomObtainIronPickaxe(_CustomObtain):
    """6000-step (5 min) iron-pickaxe hunt (11 milestones, quits on craft)."""

    def __init__(self, dense: bool = False, break_speed: int = 100, **kwargs: Any):
        super().__init__(obtain_iron_pickaxe_spec, dense, break_speed, **kwargs)
