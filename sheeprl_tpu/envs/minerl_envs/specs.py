"""Declarative task-spec data for the custom MineRL environments.

The reference defines its custom Navigate / ObtainDiamond / ObtainIronPickaxe
tasks imperatively inside minerl ``EnvSpec`` subclasses (reference:
sheeprl/envs/minerl_envs/navigate.py:18-97, obtain.py:23-281).  Here the
task *content* — observable inventory items, action vocabularies, reward
schedules, quit conditions, world setup — lives in plain-Python spec records
so it can be validated and unit-tested without the ``minerl`` package; the
gated builders in :mod:`backend`, :mod:`navigate` and :mod:`obtain` turn a
record into a real minerl ``EnvSpec`` when the backend is installed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

NONE = "none"
OTHER = "other"

#: Keyboard actions every custom task exposes (reference: backend.py:16).
SIMPLE_KEYBOARD_ACTIONS = (
    "forward",
    "back",
    "left",
    "right",
    "jump",
    "sneak",
    "sprint",
    "attack",
)

#: The item-collection milestones toward an iron pickaxe, in order, with the
#: reward granted the first time each is obtained.
IRON_PICKAXE_MILESTONES: Tuple[Tuple[str, float], ...] = (
    ("log", 1.0),
    ("planks", 2.0),
    ("stick", 4.0),
    ("crafting_table", 4.0),
    ("wooden_pickaxe", 8.0),
    ("cobblestone", 16.0),
    ("furnace", 32.0),
    ("stone_pickaxe", 32.0),
    ("iron_ore", 64.0),
    ("iron_ingot", 128.0),
    ("iron_pickaxe", 256.0),
)

#: ObtainDiamond adds the diamond itself on top of the iron-pickaxe chain.
DIAMOND_MILESTONES: Tuple[Tuple[str, float], ...] = IRON_PICKAXE_MILESTONES + (
    ("diamond", 1024.0),
)

#: Items whose counts the obtain tasks observe (a task-local inventory
#: vector when ``multihot_inventory=False``).
OBTAIN_INVENTORY_ITEMS = (
    "dirt",
    "coal",
    "torch",
    "log",
    "planks",
    "stick",
    "crafting_table",
    "wooden_axe",
    "wooden_pickaxe",
    "stone",
    "cobblestone",
    "furnace",
    "stone_axe",
    "stone_pickaxe",
    "iron_ore",
    "iron_ingot",
    "iron_axe",
    "iron_pickaxe",
)

#: Equipment types the obtain tasks can observe in the main hand.
OBTAIN_EQUIP_ITEMS = (
    "air",
    "wooden_axe",
    "wooden_pickaxe",
    "stone_axe",
    "stone_pickaxe",
    "iron_axe",
    "iron_pickaxe",
    OTHER,
)


@dataclass(frozen=True)
class RewardMilestone:
    item: str
    amount: int
    reward: float


@dataclass(frozen=True)
class MineRLTaskSpec:
    """Everything needed to instantiate one custom MineRL task."""

    name: str
    #: inventory items observed (task-local vector)
    inventory_items: Tuple[str, ...]
    #: enum vocabularies for each enum action the task exposes
    place_items: Tuple[str, ...] = (NONE,)
    equip_items: Tuple[str, ...] = ()
    craft_items: Tuple[str, ...] = ()
    nearby_craft_items: Tuple[str, ...] = ()
    nearby_smelt_items: Tuple[str, ...] = ()
    #: observed mainhand equipment vocabulary ('' = no equipment obs)
    equipment_obs_items: Tuple[str, ...] = ()
    #: compass observation (navigate tasks)
    compass: bool = False
    #: reward schedule: milestones rewarded once (or per-collection if dense)
    milestones: Tuple[Tuple[str, float], ...] = ()
    #: +reward for touching one of these block types, once per episode
    touch_block_rewards: Tuple[Tuple[str, float], ...] = ()
    #: dense navigate shaping: reward per block moved toward the compass target
    distance_reward_per_block: Optional[float] = None
    #: episode ends when the agent possesses / crafts one of these
    quit_on_possess: Tuple[Tuple[str, int], ...] = ()
    quit_on_craft: Tuple[Tuple[str, int], ...] = ()
    quit_on_touch: Tuple[str, ...] = ()
    #: world generation: "default" or a biome id
    biome: Optional[int] = None
    #: initial inventory, e.g. a compass for navigate
    start_inventory: Tuple[Tuple[str, int], ...] = ()
    #: success threshold on the total episode reward
    success_reward: Optional[float] = None
    #: whether world time passes / mobs spawn
    time_passes: bool = True
    allow_spawning: bool = True


def navigate_spec(dense: bool, extreme: bool) -> MineRLTaskSpec:
    """The Navigate task family (reference: minerl_envs/navigate.py:18-97)."""
    suffix = ("Extreme" if extreme else "") + ("Dense" if dense else "")
    return MineRLTaskSpec(
        name=f"CustomMineRLNavigate{suffix}-v0",
        inventory_items=("dirt",),
        place_items=(NONE, "dirt"),
        compass=True,
        touch_block_rewards=(("diamond_block", 100.0),),
        distance_reward_per_block=1.0 if dense else None,
        quit_on_touch=("diamond_block",),
        biome=3 if extreme else None,  # extreme hills
        start_inventory=(("compass", 1),),
        success_reward=160.0 if dense else 100.0,
        time_passes=False,
        allow_spawning=False,
    )


def _obtain_base(
    name: str,
    milestones: Tuple[Tuple[str, float], ...],
    quit_on_possess: Tuple[Tuple[str, int], ...] = (),
    quit_on_craft: Tuple[Tuple[str, int], ...] = (),
) -> MineRLTaskSpec:
    return MineRLTaskSpec(
        name=name,
        inventory_items=OBTAIN_INVENTORY_ITEMS,
        place_items=(NONE, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"),
        equip_items=(
            NONE, "air", "wooden_axe", "wooden_pickaxe", "stone_axe",
            "stone_pickaxe", "iron_axe", "iron_pickaxe",
        ),
        craft_items=(NONE, "torch", "stick", "planks", "crafting_table"),
        nearby_craft_items=(
            NONE, "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
            "iron_axe", "iron_pickaxe", "furnace",
        ),
        nearby_smelt_items=(NONE, "iron_ingot", "coal"),
        equipment_obs_items=OBTAIN_EQUIP_ITEMS,
        milestones=milestones,
        quit_on_possess=quit_on_possess,
        quit_on_craft=quit_on_craft,
    )


def obtain_diamond_spec(dense: bool) -> MineRLTaskSpec:
    """ObtainDiamond (reference: minerl_envs/obtain.py:172-248)."""
    spec = _obtain_base(
        name=f"CustomMineRLObtainDiamond{'Dense' if dense else ''}-v0",
        milestones=DIAMOND_MILESTONES,
        quit_on_possess=(("diamond", 1),),
    )
    return spec


def obtain_iron_pickaxe_spec(dense: bool) -> MineRLTaskSpec:
    """ObtainIronPickaxe (reference: minerl_envs/obtain.py:251-326)."""
    spec = _obtain_base(
        name=f"CustomMineRLObtainIronPickaxe{'Dense' if dense else ''}-v0",
        milestones=IRON_PICKAXE_MILESTONES,
        quit_on_craft=(("iron_pickaxe", 1),),
    )
    return spec


#: task-id → spec factory, the registry used by the wrapper
TASK_SPECS: Dict[str, object] = {
    "custom_navigate": navigate_spec,
    "custom_obtain_diamond": obtain_diamond_spec,
    "custom_obtain_iron_pickaxe": obtain_iron_pickaxe_spec,
}


def milestone_schedule(spec: MineRLTaskSpec) -> List[RewardMilestone]:
    return [RewardMilestone(item=i, amount=1, reward=r) for i, r in spec.milestones]


def success_from_rewards(spec: MineRLTaskSpec, rewards: List[float]) -> bool:
    """Episode success from the observed reward stream.

    Navigate: total reward reaches the task threshold.  Obtain tasks: at
    least 90% of the distinct milestone rewards were seen (reference:
    obtain.py:160-169 allows a 10% miss ratio).
    """
    if spec.milestones:
        # Distinct reward values on both sides: several milestones share a
        # value (4.0, 32.0), and an observed reward only proves *a* milestone
        # of that value was hit.  (The reference compares a deduplicated set
        # against the raw 12-entry list, which makes success unreachable.)
        distinct = set(rewards)
        values = {r for _, r in spec.milestones}
        max_missing = round(len(values) * 0.1)
        return len(distinct.intersection(values)) >= len(values) - max_missing
    if spec.success_reward is not None:
        return sum(rewards) >= spec.success_reward
    return False
