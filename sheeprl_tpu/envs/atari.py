"""Atari environments (reference: sheeprl/configs/env/atari.yaml pipeline).

The reference wraps ALE envs with gymnasium's AtariPreprocessing; the same
pipeline is reproduced here (noop resets, frame-skip via action_repeat,
grayscale/resize handled by the shared factory).  Gated: requires
``ale_py`` (not bundled in this image) — a clear error tells the user.
"""

from __future__ import annotations

from typing import Any

import gymnasium as gym

try:
    import ale_py  # noqa: F401

    gym.register_envs(ale_py)
    _ALE_AVAILABLE = True
except Exception:
    _ALE_AVAILABLE = False


def make_atari_env(env_id: str, cfg: Any, render_mode: str = "rgb_array") -> gym.Env:
    if not _ALE_AVAILABLE:
        raise ImportError(
            "Atari environments need the 'ale_py' package (pip install "
            "gymnasium[atari]); it is not available in this image"
        )
    env = gym.make(env_id, render_mode=render_mode, frameskip=1)
    env = gym.wrappers.AtariPreprocessing(
        env,
        noop_max=30,
        frame_skip=cfg.env.action_repeat if cfg.env.action_repeat > 1 else 4,
        screen_size=cfg.env.screen_size,
        grayscale_obs=cfg.env.grayscale,
        scale_obs=False,
        terminal_on_life_loss=False,
    )
    return env
