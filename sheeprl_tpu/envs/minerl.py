"""MineRL (Minecraft, v0.4.4 line) suite wrapper.

Behavior parity with the reference wrapper (reference:
sheeprl/envs/minerl.py:48-322) over the custom task specs in
:mod:`sheeprl_tpu.envs.minerl_envs`:

- The MineRL backend takes a *dict* action (keyboard flags, a continuous
  camera pair, and enum actions like ``craft``/``place``).  The agent sees a
  single ``Discrete`` space instead: action 0 is the no-op and every further
  index is one backend primitive — each binary key, each 15° camera turn
  (pitch ±, yaw ±), and each non-"none" value of each enum action.  The map
  is *enumerated from the backend action space*, so it adapts to whatever
  action set the chosen task exposes; jump/sneak/sprint also press forward.
- Sticky attack/jump hold those keys for a configurable number of steps
  (attack also releases jump while held).
- Camera pitch is clamped to ``pitch_limits``; yaw wraps to [-180, 180].
- Observations become fixed-size vectors: inventory counts and their
  running max (over the full Minecraft item vocabulary when
  ``multihot_inventory`` else over the task's own item list), one-hot
  mainhand equipment, life stats ``[life, food, oxygen]``, and the compass
  angle for navigate tasks.  Frames stay channel-last ``(H, W, 3)`` uint8
  (the TPU-native NHWC layout; the reference transposes to torch's CHW).

The ``minerl`` package (plus JDK) is not available in this image: backend
construction goes through :func:`_make_backend` and the item vocabulary
through :func:`_item_vocab`, so tests exercise the conversion pipeline
against a mock backend with duck-typed enum spaces.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

CAMERA_DELTA_DEG = 15.0
#: camera primitives appended for the "camera" action key
_CAMERA_TURNS = (
    np.array([-CAMERA_DELTA_DEG, 0.0]),  # pitch down
    np.array([+CAMERA_DELTA_DEG, 0.0]),  # pitch up
    np.array([0.0, -CAMERA_DELTA_DEG]),  # yaw left
    np.array([0.0, +CAMERA_DELTA_DEG]),  # yaw right
)
_NONE = "none"


def _item_vocab() -> List[str]:
    """The full Minecraft item vocabulary (multihot inventory mode)."""
    if not _IS_MINERL_AVAILABLE:
        raise ImportError(
            "MineRL environments need the 'minerl' package (plus a JDK); "
            "it is not available in this image"
        )
    from minerl.herobraine.hero import mc  # type: ignore

    return list(mc.ALL_ITEMS)


def _make_backend(task_id: str, break_speed: int, **kwargs: Any) -> Any:
    """Instantiate one of the custom task specs and build its backend env."""
    if not _IS_MINERL_AVAILABLE:
        raise ImportError(
            "MineRL environments need the 'minerl' package (plus a JDK); "
            "it is not available in this image"
        )
    from sheeprl_tpu.envs.minerl_envs.navigate import CustomNavigate
    from sheeprl_tpu.envs.minerl_envs.obtain import CustomObtainDiamond, CustomObtainIronPickaxe

    custom_envs = {
        "custom_navigate": CustomNavigate,
        "custom_obtain_diamond": CustomObtainDiamond,
        "custom_obtain_iron_pickaxe": CustomObtainIronPickaxe,
    }
    return custom_envs[task_id.lower()](break_speed=break_speed, **kwargs).make()


def _is_enum_space(space: Any) -> bool:
    """MineRL enum actions expose their string vocabulary via ``.values``."""
    return hasattr(space, "values") and not isinstance(space, spaces.Box)


def build_action_map(action_space: Any) -> Tuple[Dict[int, Dict[str, Any]], Dict[str, Any]]:
    """Enumerate the backend's dict action space into (discrete map, noop).

    Returns ``(actions_map, noop)`` where ``actions_map[i]`` is the dict of
    backend-action overrides for discrete action ``i`` (0 = no override =
    no-op) and ``noop`` is the rest-state template every step starts from.
    """
    actions_map: Dict[int, Dict[str, Any]] = {0: {}}
    noop: Dict[str, Any] = {}
    idx = 1
    for key in action_space:
        sub = action_space[key]
        if key == "camera":
            noop[key] = np.zeros(2, dtype=np.float32)
            variants: List[Any] = list(_CAMERA_TURNS)
        elif _is_enum_space(sub):
            noop[key] = _NONE
            vocab = [v for v in list(sub.values) if v != _NONE]
            variants = vocab
        else:
            noop[key] = 0
            variants = [1]
        for v in variants:
            actions_map[idx] = {key: v}
            if key in ("jump", "sneak", "sprint"):
                actions_map[idx]["forward"] = 1
            idx += 1
    return actions_map, noop


class MineRLWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        break_speed_multiplier: Optional[int] = 100,
        multihot_inventory: bool = True,
        **kwargs: Any,
    ):
        self._height = height
        self._width = width
        self._pitch_limits = tuple(pitch_limits)
        self._sticky_attack = 0 if (break_speed_multiplier or 1) > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._multihot = multihot_inventory
        if "navigate" not in id.lower():
            kwargs.pop("extreme", None)

        self.env = _make_backend(
            id, break_speed_multiplier, resolution=(height, width), **kwargs
        )
        self.actions_map, self._noop = build_action_map(self.env.action_space)
        self.action_space = spaces.Discrete(len(self.actions_map))

        backend_obs = self.env.observation_space
        if self._multihot:
            vocab = _item_vocab()
        else:
            vocab = list(backend_obs["inventory"])
        self.inventory_item_to_id = {name: i for i, name in enumerate(vocab)}
        self.inventory_size = len(vocab)

        obs_space: Dict[str, spaces.Space] = {
            "rgb": spaces.Box(0, 255, (height, width, 3), np.uint8),
            "life_stats": spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
            "inventory": spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
            "max_inventory": spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
        }
        if "compass" in backend_obs.spaces:
            obs_space["compass"] = spaces.Box(-180.0, 180.0, (1,), np.float32)
        if "equipped_items" in backend_obs.spaces:
            if self._multihot:
                self.equip_item_to_id = self.inventory_item_to_id
                self.equip_size = self.inventory_size
            else:
                equip_vocab = list(backend_obs["equipped_items"]["mainhand"]["type"].values)
                self.equip_item_to_id = {name: i for i, name in enumerate(equip_vocab)}
                self.equip_size = len(equip_vocab)
            obs_space["equipment"] = spaces.Box(0.0, 1.0, (self.equip_size,), np.int32)
        self.observation_space = spaces.Dict(obs_space)

        self._pos = {"pitch": 0.0, "yaw": 0.0}
        self._max_inventory = np.zeros(self.inventory_size, dtype=np.float32)
        self._render_mode = "rgb_array"
        self.seed(seed)

    # -- gym plumbing ------------------------------------------------------
    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def seed(self, seed: Optional[int] = None) -> None:
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # -- action conversion -------------------------------------------------
    def _convert_action(self, action: np.ndarray) -> Dict[str, Any]:
        out = {k: (v.copy() if isinstance(v, np.ndarray) else v) for k, v in self._noop.items()}
        out.update(self.actions_map[int(np.asarray(action).item())])
        if self._sticky_attack:
            if out.get("attack"):
                self._sticky_attack_counter = self._sticky_attack
            if self._sticky_attack_counter > 0:
                out["attack"] = 1
                out["jump"] = 0  # holding attack releases jump
                self._sticky_attack_counter -= 1
        if self._sticky_jump:
            if out.get("jump"):
                self._sticky_jump_counter = self._sticky_jump
            if self._sticky_jump_counter > 0:
                out["jump"] = 1
                out["forward"] = 1
                self._sticky_jump_counter -= 1
        return out

    # -- observation conversion --------------------------------------------
    def _convert_inventory(self, inventory: Dict[str, Any]) -> Dict[str, np.ndarray]:
        counts = np.zeros(self.inventory_size, dtype=np.float32)
        for item, qty in inventory.items():
            idx = self.inventory_item_to_id.get(item)
            if idx is None:  # outside the task's observed item list
                continue
            # "air" reports stack counts; count one per occurrence instead
            counts[idx] += 1.0 if item == "air" else float(np.asarray(qty).item())
        self._max_inventory = np.maximum(counts, self._max_inventory)
        return {"inventory": counts, "max_inventory": self._max_inventory.copy()}

    def _convert_equipment(self, equipped: Dict[str, Any]) -> np.ndarray:
        onehot = np.zeros(self.equip_size, dtype=np.int32)
        name = equipped["mainhand"]["type"]
        onehot[self.equip_item_to_id.get(name, self.equip_item_to_id["air"])] = 1
        return onehot

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        out = {
            "rgb": np.asarray(obs["pov"]).copy(),  # already HWC — TPU-native layout
            "life_stats": np.array(
                [obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["air"]],
                dtype=np.float32,
            ),
            **self._convert_inventory(obs["inventory"]),
        }
        if "equipment" in self.observation_space.spaces:
            out["equipment"] = self._convert_equipment(obs["equipped_items"])
        if "compass" in self.observation_space.spaces:
            out["compass"] = np.asarray(obs["compass"]["angle"], dtype=np.float32).reshape(1)
        return out

    # -- env API -----------------------------------------------------------
    def step(self, action: np.ndarray) -> Tuple[Dict[str, Any], float, bool, bool, Dict[str, Any]]:
        converted = self._convert_action(action)
        camera = np.asarray(converted["camera"], dtype=np.float32)
        next_pitch = self._pos["pitch"] + float(camera[0])
        next_yaw = ((self._pos["yaw"] + float(camera[1])) + 180.0) % 360.0 - 180.0
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted["camera"] = np.array([0.0, camera[1]], dtype=np.float32)
            next_pitch = self._pos["pitch"]

        obs, reward, done, info = self.env.step(converted)
        self._pos = {"pitch": next_pitch, "yaw": next_yaw}
        # MineRL cannot distinguish a true terminal from its own time limit;
        # the framework's TimeLimit wrapper supplies truncations.
        return self._convert_obs(obs), float(reward), bool(done), False, dict(info)

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        obs = self.env.reset()
        self._max_inventory = np.zeros(self.inventory_size, dtype=np.float32)
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        return self._convert_obs(obs), {}

    def render(self) -> Optional[np.ndarray]:
        return self.env.render(self._render_mode)

    def close(self) -> None:
        self.env.close()
