"""MineRL wrapper (reference: sheeprl/envs/minerl.py:48 + custom env specs
in sheeprl/envs/minerl_envs/, 526 LoC: CustomNavigate, CustomObtainDiamond,
BreakSpeedMultiplier). Gated: the 'minerl' package (and its Java backend)
is not available in this image; the wrapper surface is declared so configs
compose and users get an actionable error."""

from __future__ import annotations

from typing import Any

try:
    import minerl  # type: ignore  # noqa: F401

    _MINERL_AVAILABLE = True
except Exception:
    _MINERL_AVAILABLE = False


class MineRLWrapper:
    def __init__(self, *args: Any, **kwargs: Any):
        if not _MINERL_AVAILABLE:
            raise ImportError(
                "MineRL environments need the 'minerl' package (plus a JDK); "
                "they are not available in this image"
            )
        raise NotImplementedError(
            "MineRL support is declared but not yet implemented in this build; "
            "see sheeprl_tpu/envs/minerl.py"
        )
