"""MineDojo wrapper (reference: sheeprl/envs/minedojo.py:56, incl. action
masks). Gated: 'minedojo' is not available in this image."""

from __future__ import annotations

from typing import Any

try:
    import minedojo  # type: ignore  # noqa: F401

    _MINEDOJO_AVAILABLE = True
except Exception:
    _MINEDOJO_AVAILABLE = False


class MineDojoWrapper:
    def __init__(self, *args: Any, **kwargs: Any):
        if not _MINEDOJO_AVAILABLE:
            raise ImportError(
                "MineDojo environments need the 'minedojo' package; "
                "it is not available in this image"
            )
        raise NotImplementedError(
            "MineDojo support is declared but not yet implemented in this build"
        )
