"""MineDojo (Minecraft) suite wrapper.

Behavior parity with the reference wrapper (reference:
sheeprl/envs/minedojo.py:56-307), redesigned around a declarative compound
action table:

- The MineDojo backend takes an 8-slot MultiDiscrete action
  ``[move, strafe, jump/sneak/sprint, pitch, yaw, functional, craft_arg,
  inventory_slot]`` (camera bins are 15° with 12 = no rotation; functional
  values are 1=use 2=drop 3=attack 4=craft 5=equip 6=place 7=destroy).
  The agent instead sees a 3-slot MultiDiscrete ``[compound_action,
  craft_item, inventory_item]`` where ``compound_action`` indexes the 19
  curated combos in :data:`ACTION_MAP` (12 movement/camera + 7 functional).
- Observations are converted to fixed-size vectors over the full MineDojo
  item vocabulary: inventory counts / running max / craft deltas, one-hot
  equipment, life stats, plus four boolean action masks (action type,
  equip/place, destroy, craft/smelt) that policies can use to mask logits.
- Sticky attack/jump repeat those actions for a configurable number of
  steps, and camera pitch is clamped to ``pitch_limits``.

The ``minedojo`` package (and its Java/Malmo backend) is not available in
this image: backend construction goes through :func:`_make_backend` and the
item vocabulary through :func:`_item_vocab`, so tests exercise the full
conversion pipeline against a mock simulator and a tiny vocabulary.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

CAMERA_NOOP = 12  # 25-bin camera discretization, 15° per bin
CAMERA_DELTA_DEG = 15.0
# functional-action slot values in the backend action vector
FN_NOOP, FN_USE, FN_DROP, FN_ATTACK, FN_CRAFT, FN_EQUIP, FN_PLACE, FN_DESTROY = range(8)
# backend action-vector slots
SLOT_MOVE, SLOT_STRAFE, SLOT_JUMP, SLOT_PITCH, SLOT_YAW, SLOT_FN, SLOT_CRAFT_ARG, SLOT_INV_ARG = range(8)


def _compound(move=0, strafe=0, jump=0, pitch=CAMERA_NOOP, yaw=CAMERA_NOOP, fn=FN_NOOP) -> np.ndarray:
    return np.array([move, strafe, jump, pitch, yaw, fn, 0, 0])


#: The 19 curated compound actions exposed to the agent.
ACTION_MAP: Dict[int, np.ndarray] = {
    i: a
    for i, a in enumerate(
        [
            _compound(),                        # 0  no-op
            _compound(move=1),                  # 1  forward
            _compound(move=2),                  # 2  back
            _compound(strafe=1),                # 3  strafe left
            _compound(strafe=2),                # 4  strafe right
            _compound(move=1, jump=1),          # 5  jump + forward
            _compound(move=1, jump=2),          # 6  sneak + forward
            _compound(move=1, jump=3),          # 7  sprint + forward
            _compound(pitch=CAMERA_NOOP - 1),   # 8  pitch down 15°
            _compound(pitch=CAMERA_NOOP + 1),   # 9  pitch up 15°
            _compound(yaw=CAMERA_NOOP - 1),     # 10 yaw left 15°
            _compound(yaw=CAMERA_NOOP + 1),     # 11 yaw right 15°
        ]
        + [_compound(fn=f) for f in range(FN_USE, FN_DESTROY + 1)]  # 12..18
    )
}
N_MOVEMENT_ACTIONS = 12  # actions 0-11 are always legal


def _item_vocab() -> Tuple[List[str], List[str]]:
    """(all_items, craft_smelt_items) from the minedojo package."""
    if not _IS_MINEDOJO_AVAILABLE:
        raise ImportError(
            "MineDojo environments need the 'minedojo' package (plus a JDK); "
            "it is not available in this image"
        )
    from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS  # type: ignore

    return list(ALL_ITEMS), list(ALL_CRAFT_SMELT_ITEMS)


def _make_backend(
    task_id: str,
    image_size: Tuple[int, int],
    world_seed: Optional[int],
    break_speed_multiplier: int,
    **kwargs: Any,
) -> Any:
    """Build the raw MineDojo simulator for ``task_id``.

    MineDojo mutates its global task-spec registry during ``make``; snapshot
    and restore it so repeated constructions stay deterministic.
    """
    if not _IS_MINEDOJO_AVAILABLE:
        raise ImportError(
            "MineDojo environments need the 'minedojo' package (plus a JDK); "
            "it is not available in this image"
        )
    import minedojo  # type: ignore
    import minedojo.tasks  # type: ignore

    specs_snapshot = copy.deepcopy(minedojo.tasks.ALL_TASKS_SPECS)
    try:
        return minedojo.make(
            task_id=task_id,
            image_size=image_size,
            world_seed=world_seed,
            fast_reset=True,
            break_speed_multiplier=break_speed_multiplier,
            **kwargs,
        )
    finally:
        minedojo.tasks.ALL_TASKS_SPECS = specs_snapshot


def _norm_name(item: str) -> str:
    return "_".join(item.split(" "))


class MineDojoWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        **kwargs: Any,
    ):
        self._pitch_limits = tuple(pitch_limits)
        self._pos: Optional[Dict[str, float]] = kwargs.get("start_position", None)
        self._break_speed_multiplier = int(kwargs.pop("break_speed_multiplier", 100))
        self._start_pos = copy.deepcopy(self._pos)
        # A >1 break-speed already collapses mining to few ticks; holding the
        # attack button down on top of it would overshoot.
        self._sticky_attack = 0 if self._break_speed_multiplier > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0

        if self._pos is not None and not (
            self._pitch_limits[0] <= self._pos["pitch"] <= self._pitch_limits[1]
        ):
            raise ValueError(
                f"start_position pitch {self._pos['pitch']} outside limits {self._pitch_limits}"
            )

        all_items, craft_items = _item_vocab()
        self._item_names = all_items
        self._n_items = len(all_items)
        self._item_to_id = {name: i for i, name in enumerate(all_items)}
        self._id_to_item = dict(enumerate(all_items))
        self._n_craft = len(craft_items)

        self.env = _make_backend(
            id, (height, width), seed, self._break_speed_multiplier, **kwargs
        )

        # per-episode state filled by _convert_obs
        self._inventory_slots: Dict[str, List[int]] = {}
        self._slot_names: np.ndarray = np.array([], dtype=object)
        self._inventory_max = np.zeros(self._n_items, dtype=np.float32)

        self.action_space = spaces.MultiDiscrete(
            np.array([len(ACTION_MAP), self._n_craft, self._n_items])
        )
        n = self._n_items
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(0, 255, self.env.observation_space["rgb"].shape, np.uint8),
                "inventory": spaces.Box(0.0, np.inf, (n,), np.float32),
                "inventory_max": spaces.Box(0.0, np.inf, (n,), np.float32),
                "inventory_delta": spaces.Box(-np.inf, np.inf, (n,), np.float32),
                "equipment": spaces.Box(0.0, 1.0, (n,), np.int32),
                "life_stats": spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
                "mask_action_type": spaces.Box(0, 1, (len(ACTION_MAP),), bool),
                "mask_equip_place": spaces.Box(0, 1, (n,), bool),
                "mask_destroy": spaces.Box(0, 1, (n,), bool),
                "mask_craft_smelt": spaces.Box(0, 1, (self._n_craft,), bool),
            }
        )
        self._render_mode = "rgb_array"
        self.seed(seed)

    # -- gym plumbing ------------------------------------------------------
    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def seed(self, seed: Optional[int] = None) -> None:
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # -- observation conversion --------------------------------------------
    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        """Slot-wise inventory → per-item count vector; records the slot map
        used to translate item-indexed equip/place/destroy actions back to
        backend slot numbers."""
        counts = np.zeros(self._n_items, dtype=np.float32)
        self._inventory_slots = {}
        names = [_norm_name(item) for item in inventory["name"].tolist()]
        self._slot_names = np.array(names, dtype=object)
        for slot, (item, qty) in enumerate(zip(names, inventory["quantity"])):
            self._inventory_slots.setdefault(item, []).append(slot)
            # "air" slots report a quantity per stack-size; count slots instead
            counts[self._item_to_id[item]] += 1.0 if item == "air" else float(qty)
        self._inventory_max = np.maximum(counts, self._inventory_max)
        return counts

    def _convert_inventory_delta(self, delta: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(self._n_items, dtype=np.float32)
        for names_key, qty_key, sign in (
            ("inc_name_by_craft", "inc_quantity_by_craft", +1.0),
            ("dec_name_by_craft", "dec_quantity_by_craft", -1.0),
            ("inc_name_by_other", "inc_quantity_by_other", +1.0),
            ("dec_name_by_other", "dec_quantity_by_other", -1.0),
        ):
            for item, qty in zip(delta[names_key], delta[qty_key]):
                out[self._item_to_id[_norm_name(item)]] += sign * float(qty)
        return out

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        onehot = np.zeros(self._n_items, dtype=np.int32)
        onehot[self._item_to_id[_norm_name(equipment["name"][0])]] = 1
        return onehot

    def _convert_masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Backend per-slot masks → per-item masks over the full vocabulary,
        plus the compound-action legality mask."""
        equip_mask = np.zeros(self._n_items, dtype=bool)
        destroy_mask = np.zeros(self._n_items, dtype=bool)
        for name, can_equip, can_destroy in zip(self._slot_names, masks["equip"], masks["destroy"]):
            idx = self._item_to_id[name]
            equip_mask[idx] |= bool(can_equip)
            destroy_mask[idx] |= bool(can_destroy)
        fn_mask = np.asarray(masks["action_type"], dtype=bool).copy()
        # equip/place (functional 5, 6) need at least one equippable item,
        # destroy (functional 7) at least one destroyable one
        fn_mask[FN_EQUIP:FN_PLACE + 1] &= bool(equip_mask.any())
        fn_mask[FN_DESTROY] &= bool(destroy_mask.any())
        action_type = np.concatenate(
            [np.ones(N_MOVEMENT_ACTIONS, dtype=bool), fn_mask[FN_USE:]]
        )
        return {
            "mask_action_type": action_type,
            "mask_equip_place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": np.asarray(masks["craft_smelt"], dtype=bool),
        }

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": obs["rgb"].copy(),
            "inventory": self._convert_inventory(obs["inventory"]),
            "inventory_max": self._inventory_max,
            "inventory_delta": self._convert_inventory_delta(obs["delta_inv"]),
            "equipment": self._convert_equipment(obs["equipment"]),
            "life_stats": np.concatenate(
                (obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["oxygen"])
            ).astype(np.float32),
            **self._convert_masks(obs["masks"]),
        }

    # -- action conversion -------------------------------------------------
    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        out = ACTION_MAP[int(action[0])].copy()
        if self._sticky_attack:
            if out[SLOT_FN] == FN_ATTACK:
                self._sticky_attack_counter = self._sticky_attack - 1
            elif out[SLOT_FN] == FN_NOOP and self._sticky_attack_counter > 0:
                out[SLOT_FN] = FN_ATTACK
                self._sticky_attack_counter -= 1
            else:  # a different functional action interrupts the hold
                self._sticky_attack_counter = 0
        if self._sticky_jump:
            if out[SLOT_JUMP] == 1:
                self._sticky_jump_counter = self._sticky_jump - 1
            elif self._sticky_jump_counter > 0 and out[SLOT_MOVE] == 0:
                out[SLOT_JUMP] = 1
                if out[SLOT_STRAFE] == 0:
                    out[SLOT_MOVE] = 1  # keep moving through the held jump
                self._sticky_jump_counter -= 1
            elif out[SLOT_JUMP] != 1:
                self._sticky_jump_counter = 0
        # argument slots only accompany their functional action
        out[SLOT_CRAFT_ARG] = int(action[1]) if out[SLOT_FN] == FN_CRAFT else 0
        if out[SLOT_FN] in (FN_EQUIP, FN_PLACE, FN_DESTROY):
            slots = self._inventory_slots.get(self._id_to_item[int(action[2])], [0])
            out[SLOT_INV_ARG] = slots[0]
        else:
            out[SLOT_INV_ARG] = 0
        return out

    # -- env API -----------------------------------------------------------
    def step(self, action: np.ndarray) -> Tuple[Any, float, bool, bool, Dict[str, Any]]:
        raw = np.asarray(action)
        converted = self._convert_action(raw)
        next_pitch = self._pos["pitch"] + (converted[SLOT_PITCH] - CAMERA_NOOP) * CAMERA_DELTA_DEG
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted[SLOT_PITCH] = CAMERA_NOOP

        obs, reward, done, info = self.env.step(converted)
        timed_out = bool(info.get("TimeLimit.truncated", False))
        self._pos = self._location_stats(obs)
        info = dict(info)
        info.update(
            {
                "life_stats": {
                    "life": float(obs["life_stats"]["life"].item()),
                    "oxygen": float(obs["life_stats"]["oxygen"].item()),
                    "food": float(obs["life_stats"]["food"].item()),
                },
                "location_stats": copy.deepcopy(self._pos),
                "action": raw.tolist(),
                "biomeid": float(obs["location_stats"]["biome_id"].item()),
            }
        )
        return (
            self._convert_obs(obs),
            float(reward),
            bool(done) and not timed_out,
            bool(done) and timed_out,
            info,
        )

    @staticmethod
    def _location_stats(obs: Dict[str, Any]) -> Dict[str, float]:
        loc = obs["location_stats"]
        return {
            "x": float(loc["pos"][0]),
            "y": float(loc["pos"][1]),
            "z": float(loc["pos"][2]),
            "pitch": float(loc["pitch"].item()),
            "yaw": float(loc["yaw"].item()),
        }

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        obs = self.env.reset()
        self._pos = self._location_stats(obs)
        self._sticky_jump_counter = 0
        self._sticky_attack_counter = 0
        self._inventory_max = np.zeros(self._n_items, dtype=np.float32)
        info = {
            "life_stats": {
                "life": float(obs["life_stats"]["life"].item()),
                "oxygen": float(obs["life_stats"]["oxygen"].item()),
                "food": float(obs["life_stats"]["food"].item()),
            },
            "location_stats": copy.deepcopy(self._pos),
            "biomeid": float(obs["location_stats"]["biome_id"].item()),
        }
        return self._convert_obs(obs), info

    def render(self) -> Optional[np.ndarray]:
        if self._render_mode == "rgb_array":
            prev = getattr(self.env.unwrapped, "_prev_obs", None)
            return None if prev is None else prev["rgb"]
        return self.env.render()

    def close(self) -> None:
        self.env.close()
