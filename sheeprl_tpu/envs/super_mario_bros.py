"""Super Mario Bros wrapper (reference: sheeprl/envs/super_mario_bros.py:26). Gated."""

from __future__ import annotations

from typing import Any

try:
    import gym_super_mario_bros  # type: ignore  # noqa: F401

    _SMB_AVAILABLE = True
except Exception:
    _SMB_AVAILABLE = False


class SuperMarioBrosWrapper:
    def __init__(self, *args: Any, **kwargs: Any):
        if not _SMB_AVAILABLE:
            raise ImportError(
                "Super Mario Bros environments need 'gym-super-mario-bros'; "
                "it is not available in this image"
            )
        raise NotImplementedError(
            "Super Mario Bros support is declared but not yet implemented in this build"
        )
