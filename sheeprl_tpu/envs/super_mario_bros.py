"""Super Mario Bros suite wrapper.

Behavior parity with the reference wrapper (reference:
sheeprl/envs/super_mario_bros.py:26-70): the NES backend exposes the old
gym 4-tuple API and a joypad-button action set; this wrapper converts it to
a gymnasium Dict-observation env with a Discrete action space.

- ``action_space`` selects one of the published NES button combo sets
  ("right_only" / "simple" / "complex").
- ``step`` splits the backend's single ``done`` into terminated/truncated
  using the in-game timer: ``info["time"]`` reaching 0 is a time limit,
  i.e. a truncation, not a true terminal.  (Deliberate deviation: the
  reference tests the raw timer value as a boolean, which classifies any
  death-with-time-remaining as a truncation; here the timer must actually
  have expired.)
- Observations are wrapped as ``{"rgb": frame}`` channel-last uint8 (the
  TPU-native NHWC layout used throughout this framework).

The backend (``gym_super_mario_bros`` + ``nes_py``) is not available in this
image; construction is routed through :func:`_make_backend` so tests can
exercise the full conversion logic against a mock NES env.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_SMB_AVAILABLE

# Published NES joypad combo sets, by name. Resolved lazily from the backend
# package when present (they live in gym_super_mario_bros.actions).
ACTION_SET_NAMES = ("right_only", "simple", "complex")


def _make_backend(env_id: str, action_set: str) -> Any:
    """Build the raw NES env with the requested joypad action set.

    Returns an object with the *old gym* API: ``reset(seed, options) -> obs``
    and ``step(a) -> (obs, reward, done, info)``, plus an ``action_space``
    with ``.n`` and an image ``observation_space``.
    """
    if not _IS_SMB_AVAILABLE:
        raise ImportError(
            "Super Mario Bros environments need 'gym-super-mario-bros' (and "
            "'nes-py'); they are not available in this image"
        )
    import gym_super_mario_bros as gsmb  # type: ignore
    from gym_super_mario_bros.actions import (  # type: ignore
        COMPLEX_MOVEMENT,
        RIGHT_ONLY,
        SIMPLE_MOVEMENT,
    )
    from nes_py.wrappers import JoypadSpace  # type: ignore

    combos = {
        "right_only": RIGHT_ONLY,
        "simple": SIMPLE_MOVEMENT,
        "complex": COMPLEX_MOVEMENT,
    }[action_set]

    class _SeedableJoypad(JoypadSpace):  # reset(seed=...) passthrough
        def reset(self, seed: Optional[int] = None, options: Optional[dict] = None):
            return self.env.reset(seed=seed, options=options)

    return _SeedableJoypad(gsmb.make(env_id), combos)


class SuperMarioBrosWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        action_space: str = "simple",
        render_mode: str = "rgb_array",
    ):
        if action_space not in ACTION_SET_NAMES:
            raise ValueError(
                f"Unknown SMB action set '{action_space}'; options: {ACTION_SET_NAMES}"
            )
        self.env = _make_backend(id, action_space)
        self._render_mode = render_mode

        backend_obs = self.env.observation_space
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(
                    np.asarray(backend_obs.low),
                    np.asarray(backend_obs.high),
                    backend_obs.shape,
                    backend_obs.dtype,
                )
            }
        )
        self.action_space = spaces.Discrete(int(self.env.action_space.n))

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    @render_mode.setter
    def render_mode(self, mode: str) -> None:
        self._render_mode = mode

    def step(self, action: Any) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        if isinstance(action, np.ndarray):
            action = int(action.squeeze().item())
        result = self.env.step(action)
        if len(result) == 5:  # new-API backend: already split
            obs, reward, terminated, truncated, info = result
            done = bool(terminated) or bool(truncated)
            if truncated:
                info = {**info, "TimeLimit.truncated": True}
        else:
            obs, reward, done, info = result
        # The NES game over on timer expiry is a time limit, not a death:
        # report it as truncation so value bootstrapping stays correct.
        timed_out = bool(info.get("time", 1) == 0) or bool(info.get("TimeLimit.truncated", False))
        terminated = bool(done) and not timed_out
        truncated = bool(done) and timed_out
        return {"rgb": np.asarray(obs).copy()}, float(reward), terminated, truncated, dict(info)

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        obs = self.env.reset(seed=seed, options=options)
        if isinstance(obs, tuple):  # tolerate new-API backends
            obs = obs[0]
        return {"rgb": np.asarray(obs).copy()}, {}

    def render(self) -> Optional[np.ndarray]:
        frame = self.env.render(mode=self._render_mode) if self._render_mode else None
        if self._render_mode == "rgb_array" and frame is not None:
            return np.asarray(frame).copy()
        return None

    def close(self) -> None:
        self.env.close()
