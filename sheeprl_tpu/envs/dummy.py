"""Deterministic dummy environments for the test harness.

Parity with the reference's dummy envs (reference: sheeprl/envs/dummy.py:8-108):
Dict observations (an ``rgb`` image + a ``state`` vector), fixed-length
episodes, and discrete / multi-discrete / continuous action variants.  Images
are channel-last ``(H, W, C)`` (the TPU-native layout used framework-wide).

Env-contract note (ISSUE 11, scenario matrix): the dummy family exposes the
SAME seeding/auto-reset surface as the gym and jax env families —
``reset(seed=)`` seeds ``np_random`` and (with ``random_start=True``)
yields seed-reproducible, seed-distinct trajectories; through
``utils.env.vectorize`` the SAME_STEP auto-reset surfaces
``final_obs``/``final_info`` exactly like any other env.  The DEFAULTS stay
bit-identical to the historical behavior (step counter from 0, fixed-length
episodes ending in ``terminated``): the golden/regression fixtures train on
these envs and must not drift.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces


class _DummyEnv(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(
        self,
        image_size: Tuple[int, int, int] = (64, 64, 3),
        episode_len: int = 128,
        random_start: bool = False,
    ):
        self._image_size = image_size
        self._episode_len = episode_len
        # random_start=False (default) keeps the historical deterministic
        # trajectories (goldens); True makes seeding OBSERVABLE — the step
        # counter starts at a seeded draw, so same-seed resets reproduce
        # and different seeds diverge (the contract the scenario matrix
        # asserts across all three env families)
        self._random_start = bool(random_start)
        self._step = 0
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(0, 255, image_size, np.uint8),
                "state": spaces.Box(-np.inf, np.inf, (4,), np.float32),
            }
        )
        self.reward_range = (0.0, 1.0)

    def _obs(self) -> Dict[str, np.ndarray]:
        return {
            "rgb": np.full(self._image_size, self._step % 256, dtype=np.uint8),
            "state": np.full((4,), self._step, dtype=np.float32),
        }

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        super().reset(seed=seed)
        self._step = (
            int(self.np_random.integers(self._episode_len // 2)) if self._random_start else 0
        )
        return self._obs(), {}

    def step(self, action: Any):
        self._step += 1
        done = self._step >= self._episode_len
        return self._obs(), 1.0, done, False, {}

    def render(self) -> np.ndarray:
        return self._obs()["rgb"]


class DiscreteDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.Discrete(4)


class PixelGridDummyEnv(gym.Env):
    """A LEARNABLE pixel task for CPU-budget learning validation (the plain
    dummy envs pay a constant reward, so nothing can be learned from them).

    A ``grid × grid`` world rendered onto a 64×64×3 image: the agent is a
    white patch, the goal a green patch at a fixed cell.  Actions
    (noop/up/down/left/right) move the agent one cell; the reward each step
    is the negative normalized Manhattan distance to the goal.  The agent's
    position appears ONLY in the pixels (the ``state`` key is zeros), so a
    policy can beat random exclusively through the CNN trunk — giving the
    pixel encoder/decoder and two-hot reward head real learning teeth
    (VERDICT r3 weak #3: the DV3 learning test was vector-obs only).
    """

    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(self, grid: int = 4, episode_len: int = 16, image_hw: int = 64):
        self._grid = grid
        self._cell = image_hw // grid
        self._episode_len = episode_len
        self._hw = image_hw
        self._goal = (grid - 1, grid - 1)
        self._pos = [0, 0]
        self._step_count = 0
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(0, 255, (image_hw, image_hw, 3), np.uint8),
                "state": spaces.Box(-np.inf, np.inf, (4,), np.float32),
            }
        )
        self.action_space = spaces.Discrete(5)
        self.reward_range = (-1.0, 0.0)

    def _obs(self) -> Dict[str, np.ndarray]:
        img = np.zeros((self._hw, self._hw, 3), np.uint8)
        c = self._cell
        gy, gx = self._goal
        img[gy * c : (gy + 1) * c, gx * c : (gx + 1) * c, 1] = 255  # green goal
        y, x = self._pos
        img[y * c : (y + 1) * c, x * c : (x + 1) * c, :] = 255  # white agent
        return {"rgb": img, "state": np.zeros((4,), np.float32)}

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        super().reset(seed=seed)
        self._step_count = 0
        # random start, never on the goal
        while True:
            self._pos = [int(self.np_random.integers(self._grid)) for _ in range(2)]
            if tuple(self._pos) != self._goal:
                break
        return self._obs(), {}

    def step(self, action: Any):
        self._step_count += 1
        a = int(np.asarray(action).reshape(-1)[0])
        dy, dx = [(0, 0), (-1, 0), (1, 0), (0, -1), (0, 1)][a % 5]
        self._pos[0] = int(np.clip(self._pos[0] + dy, 0, self._grid - 1))
        self._pos[1] = int(np.clip(self._pos[1] + dx, 0, self._grid - 1))
        dist = abs(self._pos[0] - self._goal[0]) + abs(self._pos[1] - self._goal[1])
        reward = -dist / (2 * (self._grid - 1))
        done = self._step_count >= self._episode_len
        return self._obs(), float(reward), False, done, {}

    def render(self) -> np.ndarray:
        return self._obs()["rgb"]


class MultiDiscreteDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.MultiDiscrete([4, 3])


class ContinuousDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.Box(-1.0, 1.0, (2,), np.float32)
