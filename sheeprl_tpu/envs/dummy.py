"""Deterministic dummy environments for the test harness.

Parity with the reference's dummy envs (reference: sheeprl/envs/dummy.py:8-108):
Dict observations (an ``rgb`` image + a ``state`` vector), fixed-length
episodes, and discrete / multi-discrete / continuous action variants.  Images
are channel-last ``(H, W, C)`` (the TPU-native layout used framework-wide).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces


class _DummyEnv(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}
    render_mode = "rgb_array"

    def __init__(self, image_size: Tuple[int, int, int] = (64, 64, 3), episode_len: int = 128):
        self._image_size = image_size
        self._episode_len = episode_len
        self._step = 0
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(0, 255, image_size, np.uint8),
                "state": spaces.Box(-np.inf, np.inf, (4,), np.float32),
            }
        )
        self.reward_range = (0.0, 1.0)

    def _obs(self) -> Dict[str, np.ndarray]:
        return {
            "rgb": np.full(self._image_size, self._step % 256, dtype=np.uint8),
            "state": np.full((4,), self._step, dtype=np.float32),
        }

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        super().reset(seed=seed)
        self._step = 0
        return self._obs(), {}

    def step(self, action: Any):
        self._step += 1
        done = self._step >= self._episode_len
        return self._obs(), 1.0, done, False, {}

    def render(self) -> np.ndarray:
        return self._obs()["rgb"]


class DiscreteDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.Discrete(4)


class MultiDiscreteDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.MultiDiscrete([4, 3])


class ContinuousDummyEnv(_DummyEnv):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.action_space = spaces.Box(-1.0, 1.0, (2,), np.float32)
