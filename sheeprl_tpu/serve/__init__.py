"""Policy-as-a-service: a continuous-batching inference layer.

Turns any COMMITTED training snapshot (``checkpoint/protocol.py``) into a
persistent, always-warm policy endpoint:

* ``loader``  — checkpoint discovery + player-network rebuild (the single
  snapshot-reconstruction path, shared with ``sheeprl_tpu.cli:evaluation``);
* ``players`` — per-algorithm :class:`~sheeprl_tpu.serve.players.PolicyPlayer`
  builders (dreamer_v3, ppo, sac families) whose step programs are
  AOT-compiled at a fixed batch-size ladder through ``parallel/compile.py``;
* ``batcher`` — the continuous-batching engine: admission queue,
  pad-to-ladder coalescing, response scatter;
* ``reload``  — a background ``COMMIT`` watcher that hot-swaps params
  (double-buffered host→device transfer) without dropping in-flight
  requests;
* ``service`` — the in-process :class:`PolicyService` API;
* ``server``/``client`` — a stdlib HTTP surface over it;
* ``fleet``   — the fault-tolerant fleet: a health-checked router over N
  replica processes with session-carry migration and rolling reload.

See docs/serving.md for the architecture.
"""

from sheeprl_tpu.serve.batcher import AdmissionQueue, QueueFull, pick_ladder_size
from sheeprl_tpu.serve.loader import (
    build_player,
    evaluate_player,
    load_policy,
    load_run_config,
    resolve_checkpoint,
)
from sheeprl_tpu.serve.players import PLAYER_BUILDERS, PolicyPlayer, register_player
from sheeprl_tpu.serve.service import PolicyService

__all__ = [
    "AdmissionQueue",
    "FleetRouter",
    "FleetServer",
    "LocalFleet",
    "PLAYER_BUILDERS",
    "PolicyClient",
    "PolicyPlayer",
    "PolicyServer",
    "PolicyService",
    "QueueFull",
    "build_player",
    "evaluate_player",
    "load_policy",
    "load_run_config",
    "pick_ladder_size",
    "register_player",
    "resolve_checkpoint",
]


def __getattr__(name):  # lazy: server/client/fleet pull in http/urllib machinery
    if name == "PolicyServer":
        from sheeprl_tpu.serve.server import PolicyServer

        return PolicyServer
    if name == "PolicyClient":
        from sheeprl_tpu.serve.client import PolicyClient

        return PolicyClient
    if name in ("FleetRouter", "FleetServer", "LocalFleet"):
        import sheeprl_tpu.serve.fleet as fleet

        return getattr(fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
