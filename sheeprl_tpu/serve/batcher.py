"""Continuous-batching engine: admission queue, coalescer, response scatter.

Requests land in a bounded FIFO :class:`AdmissionQueue` (backpressure: a
full queue blocks or raises :class:`QueueFull`).  A single dispatcher
thread coalesces the head of the queue into one batch under a
max-batch/max-wait policy — dispatch as soon as ``max_batch`` requests are
waiting, or when the OLDEST waiting request has aged ``max_wait_ms``,
whichever comes first — pads the batch up to the nearest static ladder
size (:func:`pick_ladder_size`), runs the player's AOT executable, and
scatters per-row results back to the callers' futures.

Padding to a fixed ladder is what makes steady-state serving
recompile-free: every batch the executable ever sees has one of the
warmed shapes, so XLA never re-traces, no matter how ragged the arrival
process is.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class QueueFull(RuntimeError):
    """Admission queue at capacity — the server is shedding load."""


class ServiceStopped(RuntimeError):
    """Request rejected/failed because the service is shutting down."""


def pick_ladder_size(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder batch size that fits ``n`` rows.

    ``n`` above the ladder top is a caller bug (the coalescer never takes
    more than ``max(ladder)`` requests) — raise instead of silently
    recompiling at an unwarmed shape.
    """
    if n <= 0:
        raise ValueError(f"batch of {n} rows")
    for size in sorted(ladder):
        if n <= size:
            return int(size)
    raise ValueError(f"batch of {n} rows exceeds the ladder top {max(ladder)}")


class _Request:
    __slots__ = ("obs", "greedy", "session", "enqueued", "event", "result", "error", "cancelled")

    def __init__(self, obs: Dict[str, np.ndarray], greedy: bool, session: Optional[str]):
        self.obs = obs
        self.greedy = bool(greedy)
        self.session = session
        self.enqueued = time.perf_counter()
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False

    # -- caller side -------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.event.wait(timeout):
            # the caller is gone (HTTP 504): mark the still-queued request so
            # the dispatcher drops it instead of burning a batch slot and —
            # for stateful sessions — advancing the latent chain on an
            # observation the client will resend on retry (best-effort: a
            # dispatch that already started still completes normally)
            self.cancelled = True
            raise TimeoutError("policy request timed out")
        if self.error is not None:
            raise self.error
        return self.result

    def done(self) -> bool:
        return self.event.is_set()

    # -- dispatcher side ---------------------------------------------------
    def resolve(self, result: np.ndarray) -> None:
        self.result = result
        self.event.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.event.set()


class AdmissionQueue:
    """Bounded FIFO with coalescing pop.

    FIFO order is the fairness policy: requests are served strictly in
    arrival order, so no session can starve another, and the max-wait clock
    is anchored to the OLDEST waiting request.
    """

    def __init__(self, max_pending: int = 1024):
        self.max_pending = int(max_pending)
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def put(self, req: _Request, block: bool = True, timeout: Optional[float] = None) -> None:
        with self._lock:
            if self._closed:
                raise ServiceStopped("admission queue closed")
            if len(self._items) >= self.max_pending:
                if not block:
                    raise QueueFull(
                        f"{len(self._items)} requests pending (max_pending={self.max_pending})"
                    )
                deadline = None if timeout is None else time.monotonic() + timeout
                while len(self._items) >= self.max_pending:
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise QueueFull(
                            f"{len(self._items)} requests pending after {timeout}s "
                            f"(max_pending={self.max_pending})"
                        )
                    self._not_full.wait(remaining)
                    if self._closed:
                        raise ServiceStopped("admission queue closed")
            self._items.append(req)
            self._not_empty.notify()

    def get_batch(self, max_batch: int, max_wait_s: float) -> List[_Request]:
        """Block until at least one request is waiting, then collect up to
        ``max_batch`` requests, waiting at most ``max_wait_s`` past the
        oldest request's arrival for stragglers.  Returns ``[]`` only when
        the queue is closed and drained."""
        with self._lock:
            while not self._items:
                if self._closed:
                    return []
                self._not_empty.wait(0.1)
            # anchor the wait budget to the oldest request's age so a slow
            # trickle can't hold the head request hostage for max_wait each
            deadline = self._items[0].enqueued + max_wait_s
            while len(self._items) < max_batch and not self._closed:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)
            batch = []
            while self._items and len(batch) < max_batch:
                batch.append(self._items.popleft())
            self._not_full.notify_all()
            return batch

    def close(self) -> List[_Request]:
        """Stop admitting; return whatever was still pending (the service
        decides whether to serve or fail them)."""
        with self._lock:
            self._closed = True
            pending = list(self._items)
            self._items.clear()
            self._not_empty.notify_all()
            self._not_full.notify_all()
            return pending

    @property
    def closed(self) -> bool:
        return self._closed


class LatencyTracker:
    """Ring buffer of request latencies with percentile readout."""

    def __init__(self, window: int = 8192):
        self._lat = deque(maxlen=window)
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(seconds)

    def percentiles(self, qs: Sequence[float] = (50, 99)) -> Dict[str, float]:
        with self._lock:
            data = np.asarray(self._lat, dtype=np.float64)
        if data.size == 0:
            return {f"p{int(q)}_ms": float("nan") for q in qs}
        return {f"p{int(q)}_ms": float(np.percentile(data, q) * 1e3) for q in qs}

    def count(self) -> int:
        with self._lock:
            return len(self._lat)
