"""The in-process policy service: warm ladder, batch, serve, hot-reload.

:class:`PolicyService` glues the pieces together around one model:

* a :class:`~sheeprl_tpu.serve.players.PolicyPlayer` (AOT step program),
* the batch-size ladder, AOT-warmed through the shared
  :class:`~sheeprl_tpu.parallel.compile.CompilePool` before traffic is
  admitted (``Compile/*`` counters must stay flat afterwards),
* an :class:`~sheeprl_tpu.serve.batcher.AdmissionQueue` + one dispatcher
  thread doing pad-to-ladder coalescing,
* a :class:`~sheeprl_tpu.serve.reload.CommitWatcher` hot-swapping params on
  a new ``COMMIT`` without dropping in-flight requests,
* per-session latent carries for stateful players (dreamer_v3).

Used directly by ``bench.py --mode serve`` and the tests, and wrapped by
``serve.server`` for the HTTP surface.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from sheeprl_tpu.serve.batcher import (
    AdmissionQueue,
    LatencyTracker,
    ServiceStopped,
    _Request,
    pick_ladder_size,
)
from sheeprl_tpu.serve.reload import CommitWatcher, ParamStore

DEFAULT_LADDER = (1, 8, 32, 128)


class PolicyService:
    """Continuous-batching policy server around one committed checkpoint."""

    def __init__(
        self,
        fabric: Any,
        cfg: Any,
        player: Any,
        ckpt_root: Optional[Any] = None,
        state: Optional[Dict[str, Any]] = None,
    ):
        self.fabric = fabric
        self.cfg = cfg
        self.player = player
        self.ckpt_root = ckpt_root
        serve_cfg = cfg.get("serve") or {}
        ladder = tuple(int(b) for b in serve_cfg.get("batch_ladder", DEFAULT_LADDER))
        self.ladder = tuple(sorted(set(ladder)))
        self.max_batch = self.ladder[-1]
        self.max_wait_s = float(serve_cfg.get("max_wait_ms", 5.0)) / 1e3
        self.default_greedy = bool(serve_cfg.get("greedy", True))
        self.queue = AdmissionQueue(int(serve_cfg.get("max_pending", 1024)))
        self.store = ParamStore(player.params, step=player.checkpoint_step)
        self.latency = LatencyTracker(int(serve_cfg.get("latency_window", 8192)))
        self._poll_s = float(serve_cfg.get("reload_poll_s", 2.0))
        self._watch = bool(serve_cfg.get("watch_commits", True)) and ckpt_root is not None
        self.watcher: Optional[CommitWatcher] = None
        if ckpt_root is not None:
            self.watcher = CommitWatcher(
                ckpt_root,
                self.store,
                self._load_player_params,
                poll_s=self._poll_s,
                failure_threshold=int(serve_cfg.get("reload_failure_threshold", 3)),
                breaker_reset_s=float(serve_cfg.get("reload_breaker_reset_s", 30.0)),
                quarantine=bool(serve_cfg.get("quarantine_poisoned", True)),
            )
        self._sessions: Dict[str, tuple] = {}
        self._sessions_lock = threading.Lock()
        self._dispatcher: Optional[threading.Thread] = None
        self._seed_lock = threading.Lock()
        self._seed = int(cfg.get("seed", 0) or 0)
        self._stats_lock = threading.Lock()
        self._served = 0
        self._batches = 0
        self._padded_rows = 0
        self._errors = 0
        self._started = False

    # -- construction --------------------------------------------------------
    @classmethod
    def from_checkpoint(
        cls, checkpoint_path: Any, overrides: Sequence[str] = ()
    ) -> "PolicyService":
        from sheeprl_tpu.serve.loader import checkpoint_root, load_policy, resolve_checkpoint

        ckpt = resolve_checkpoint(checkpoint_path)
        fabric, cfg, state, player = load_policy(ckpt, overrides)
        root = checkpoint_root(ckpt) if ckpt.is_dir() else None
        return cls(fabric, cfg, player, ckpt_root=root, state=state)

    # -- lifecycle -----------------------------------------------------------
    def warm_up(self, timeout: Optional[float] = None) -> None:
        """AOT-compile the step executable at every ladder batch size (in
        parallel, via the shared CompilePool).  After this returns, steady
        state never compiles again — the acceptance gate asserts it."""
        from sheeprl_tpu.parallel.compile import warmup_batch_ladder

        warmup_batch_ladder(
            self.player.step,
            self.player.batch_specs,
            self.ladder,
            pool=self.fabric.compile_pool,
            join=True,
            timeout=timeout,
        )

    def start(self, warm: bool = True) -> "PolicyService":
        if self._started:
            return self
        if warm:
            self.warm_up()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="sheeprl-serve-dispatch", daemon=True
        )
        self._dispatcher.start()
        if self.watcher is not None and self._watch:
            self.watcher.start()
        self._started = True
        # export the serving stats through the telemetry hub: /v1/stats'
        # numbers (and the server's /metrics Prometheus view) come from the
        # same registration API every other subsystem uses
        from sheeprl_tpu.telemetry import HUB

        HUB.register("serve", self.hub_metrics)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Clean shutdown: stop admitting, serve (or fail) the backlog, join
        the threads."""
        pending = self.queue.close()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout)
        if drain and pending:
            for start in range(0, len(pending), self.max_batch):
                self._dispatch(pending[start : start + self.max_batch])
        else:
            for req in pending:
                req.fail(ServiceStopped("service stopped before dispatch"))
        if self.watcher is not None:
            self.watcher.stop()
        from sheeprl_tpu.telemetry import HUB

        HUB.unregister("serve")
        self._started = False

    def __enter__(self) -> "PolicyService":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- request path --------------------------------------------------------
    def submit(
        self,
        obs: Dict[str, np.ndarray],
        greedy: Optional[bool] = None,
        session: Optional[str] = None,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> _Request:
        """Enqueue one observation; returns a future-like request handle
        (``.wait(timeout) -> action``).  Raises
        :class:`~sheeprl_tpu.serve.batcher.QueueFull` under backpressure."""
        req = _Request(
            obs, self.default_greedy if greedy is None else greedy, session
        )
        self.queue.put(req, block=block, timeout=timeout)
        return req

    def act(
        self,
        obs: Dict[str, np.ndarray],
        greedy: Optional[bool] = None,
        session: Optional[str] = None,
        timeout: Optional[float] = 30.0,
        block: bool = True,
    ) -> np.ndarray:
        """Synchronous convenience: submit + wait.  ``block=False`` sheds
        load (raises :class:`QueueFull`) instead of blocking the caller on a
        full admission queue — the HTTP surface uses it so an overloaded
        server answers 429 rather than pinning one handler thread per
        pending connection; ``timeout`` bounds only the post-admission wait."""
        return self.submit(obs, greedy=greedy, session=session, block=block).wait(timeout)

    def reset_session(self, session: str) -> None:
        """Drop a stateful session's latent carry (episode boundary)."""
        with self._sessions_lock:
            self._sessions.pop(session, None)

    # -- carry migration (the fleet router's failover primitive) -------------
    def get_session_carry(self, session: str) -> Optional[Dict[str, Any]]:
        """Host-side, CRC-stamped snapshot of one session's latent carry.

        The wire format the fleet router mirrors and replays onto a
        surviving replica when this one dies (docs/serving.md "Fleet"):
        packed base64 leaves in ``carry_spec`` order plus a CRC over the
        raw buffers, so a torn mirror cannot silently resurrect a session
        with a corrupted latent state.  Returns None for unknown sessions
        and for stateless players (nothing to migrate).
        """
        if not self.player.stateful:
            return None
        with self._sessions_lock:
            carry = self._sessions.get(session)
        if carry is None:
            return None
        from sheeprl_tpu.serve.server import encode_array

        leaves = [np.ascontiguousarray(np.asarray(c)) for c in carry]
        return {
            "session": session,
            "algo": self.player.algo,
            "generation": self.store.generation,
            "carry": [encode_array(leaf, packed=True) for leaf in leaves],
            "crc": _carry_crc(leaves),
        }

    def restore_session_carry(self, session: str, snapshot: Dict[str, Any]) -> None:
        """Install a :meth:`get_session_carry` snapshot as ``session``'s
        carry, validating algo, leaf shapes/dtypes against ``carry_spec``
        and the CRC stamp.  Raises ValueError on any mismatch — a failed
        restore must surface to the router, never silently seed a session
        with a zero or corrupt carry."""
        if not self.player.stateful:
            raise ValueError(f"player '{self.player.algo}' is stateless: no carry to restore")
        algo = snapshot.get("algo")
        if algo not in (None, self.player.algo):
            raise ValueError(f"carry snapshot is for algo '{algo}', not '{self.player.algo}'")
        from sheeprl_tpu.serve.server import decode_array

        spec = self.player.carry_spec
        raw = snapshot.get("carry")
        if not isinstance(raw, (list, tuple)) or len(raw) != len(spec):
            got = len(raw) if isinstance(raw, (list, tuple)) else type(raw).__name__
            raise ValueError(f"carry snapshot has {got} leaves, expected {len(spec)}")
        leaves = []
        for i, (value, (shape, dtype)) in enumerate(zip(raw, spec)):
            leaf = np.ascontiguousarray(decode_array(value))
            want = (1, *shape)
            if leaf.shape != want or leaf.dtype != np.dtype(dtype):
                raise ValueError(
                    f"carry leaf {i} is {leaf.shape}/{leaf.dtype}, "
                    f"expected {want}/{dtype}"
                )
            leaves.append(leaf)
        stamp = snapshot.get("crc")
        if stamp is None or int(stamp) != _carry_crc(leaves):
            raise ValueError("carry snapshot failed its CRC check (torn or corrupted mirror)")
        with self._sessions_lock:
            self._sessions[session] = tuple(leaves)

    # -- dispatch ------------------------------------------------------------
    def _next_seed(self) -> int:
        with self._seed_lock:
            self._seed = (self._seed + 1) % (2**31 - 1)
            return self._seed

    def _dispatch_loop(self) -> None:
        while True:
            batch = self.queue.get_batch(self.max_batch, self.max_wait_s)
            if not batch:
                if self.queue.closed:
                    return
                continue
            if self.player.stateful:
                # two requests for the same session must NOT share one batch:
                # both would read the same pre-batch carry and the second
                # write would drop the first latent transition — chain them
                # through sequential waves instead
                for wave in _session_waves(batch):
                    self._dispatch(wave)
            else:
                self._dispatch(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        batch = [r for r in batch if not r.cancelled]  # 504'd while queued
        if not batch:
            return
        player = self.player
        try:
            k = len(batch)
            size = pick_ladder_size(k, self.ladder)
            # params captured ONCE per batch: a hot swap mid-batch only
            # affects the next dispatch, never rows already in flight
            params, generation, ckpt_step = self.store.snapshot()
            raw = {
                key: np.stack([np.asarray(r.obs[key]) for r in batch])
                for key in player.obs_spec
            }
            prepped = player.prepare(raw)
            obs = {key: _pad_rows(v, size) for key, v in prepped.items()}
            if player.stateful:
                rows = [self._session_carry(r.session) for r in batch]
                carry = tuple(
                    _pad_rows(np.concatenate([row[i] for row in rows], axis=0), size)
                    for i in range(len(player.carry_spec))
                )
            else:
                carry = ()
            greedy = np.zeros((size,), bool)
            greedy[:k] = [r.greedy for r in batch]
            new_carry, actions = player.step_batch(
                params, carry, obs, self._next_seed(), greedy
            )
            env_actions = player.postprocess(actions[:k])
            now = time.perf_counter()
            for i, req in enumerate(batch):
                if player.stateful and req.session is not None:
                    with self._sessions_lock:
                        self._sessions[req.session] = tuple(
                            c[i : i + 1] for c in new_carry
                        )
                self.latency.record(now - req.enqueued)
                req.resolve(np.asarray(env_actions[i]))
            with self._stats_lock:
                self._served += k
                self._batches += 1
                self._padded_rows += size - k
        except BaseException as e:
            with self._stats_lock:
                self._errors += len(batch)
            for req in batch:
                req.fail(e)

    def _session_carry(self, session: Optional[str]) -> tuple:
        if session is not None:
            with self._sessions_lock:
                carry = self._sessions.get(session)
            if carry is not None:
                return carry
        return self.player.zero_carry_row()

    def _load_player_params(self, step_dir: Any) -> Any:
        """Hot-reload read: this rank's shard off the new snapshot, then the
        player-relevant subtree host→device into fresh buffers."""
        from sheeprl_tpu.serve.players import extract_player_state

        state = self.fabric.load(step_dir)
        return extract_player_state(self.player, self.fabric, state["agent"])

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        from sheeprl_tpu.utils.profiler import COMPILE_MONITOR

        with self._stats_lock:
            served, batches = self._served, self._batches
            padded, errors = self._padded_rows, self._errors
        n_exe, compile_s = COMPILE_MONITOR.totals()
        out = {
            "algo": self.player.algo,
            "served": served,
            "batches": batches,
            "errors": errors,
            "pending": len(self.queue),
            "avg_batch": round(served / batches, 3) if batches else 0.0,
            "padded_frac": round(padded / (served + padded), 4) if served + padded else 0.0,
            "generation": self.store.generation,
            "checkpoint_step": self.store.step,
            "reloads": self.watcher.reloads if self.watcher else 0,
            "reload_error": self.watcher.last_error if self.watcher else None,
            # reload circuit breaker: open/half_open means new commits are
            # failing to load and the server keeps serving the old params
            "degraded": self.watcher.degraded if self.watcher else False,
            "reload_breaker": self.watcher.breaker.snapshot() if self.watcher else None,
            "quarantined": self.watcher.quarantined if self.watcher else 0,
            "batch_ladder": list(self.ladder),
            "compile_executables": n_exe,
            "compile_time_s": round(compile_s, 3),
            "sessions": len(self._sessions),
        }
        out.update(self.latency.percentiles((50, 99)))
        return out

    def hub_metrics(self) -> Dict[str, float]:
        """The numeric subset of :meth:`stats` as ``Serve/*`` hub metrics
        (the telemetry-hub source registered by :meth:`start`)."""
        s = self.stats()
        out: Dict[str, float] = {}
        for key in (
            "served", "batches", "errors", "pending", "avg_batch",
            "padded_frac", "generation", "checkpoint_step", "reloads",
            "quarantined", "sessions", "p50_ms", "p99_ms",
        ):
            value = s.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out[f"Serve/{key}"] = float(value)
        out["Serve/degraded"] = 1.0 if s.get("degraded") else 0.0
        return out


def _carry_crc(leaves: Sequence[np.ndarray]) -> int:
    """CRC32 over every carry leaf's shape/dtype header + raw C-order
    bytes — the integrity stamp on migrated session carries."""
    import zlib

    crc = 0
    for leaf in leaves:
        header = f"{leaf.shape}:{leaf.dtype}".encode()
        crc = zlib.crc32(header, crc)
        crc = zlib.crc32(np.ascontiguousarray(leaf).tobytes(), crc)
    return crc & 0xFFFFFFFF


def _session_waves(batch: List[_Request]) -> List[List[_Request]]:
    """Split a coalesced batch into waves holding at most ONE request per
    (non-None) session, preserving arrival order within each session.  A
    session's second pipelined request lands in the next wave, so its step
    sees the carry the first one wrote."""
    waves: List[List[_Request]] = []
    sessions: List[set] = []
    for req in batch:
        for wave, seen in zip(waves, sessions):
            if req.session is None or req.session not in seen:
                wave.append(req)
                if req.session is not None:
                    seen.add(req.session)
                break
        else:
            waves.append([req])
            sessions.append(set() if req.session is None else {req.session})
    return waves


def _pad_rows(x: np.ndarray, size: int) -> np.ndarray:
    """Pad the leading (batch) axis up to ``size`` with zeros."""
    x = np.asarray(x)
    if x.shape[0] == size:
        return x
    pad = np.zeros((size - x.shape[0], *x.shape[1:]), dtype=x.dtype)
    return np.concatenate([x, pad], axis=0)
