"""``python -m sheeprl_tpu.serve.fleet checkpoint_path=<run-dir> [overrides...]``"""

from sheeprl_tpu.cli import serve_fleet

if __name__ == "__main__":
    serve_fleet()
