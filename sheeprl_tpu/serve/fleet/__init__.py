"""Fault-tolerant serving fleet: health-checked router over N replicas.

The robustness layer over ``sheeprl_tpu.serve`` (docs/serving.md "Fleet"):

* ``router``   — :class:`FleetRouter` + :class:`FleetServer`: a stdlib-HTTP
  front doing health-checked least-loaded dispatch, per-replica circuit
  breakers (eject/readmit), rendezvous-hash session affinity with carry
  migration on replica death, and fleet-wide rolling hot reload driven by
  the same ``CommitWatcher`` machinery single servers use;
* ``replicas`` — :class:`LocalFleet`: a local replica supervisor
  (spawn/respawn with jittered backoff, the PR 14 supervisor pattern).

One address for clients, N interchangeable replica processes behind it: a
replica death costs at most one in-flight step, never a session.
"""

from sheeprl_tpu.serve.fleet.router import FleetRouter, FleetServer, ReplicaState, assign_replica
from sheeprl_tpu.serve.fleet.replicas import LocalFleet

__all__ = [
    "FleetRouter",
    "FleetServer",
    "LocalFleet",
    "ReplicaState",
    "assign_replica",
]
