"""Health-checked routing front over N policy-server replicas.

The fleet's availability story lives here (docs/serving.md "Fleet"):

* **dispatch** — stateless requests go to the least-loaded routable
  replica (in-flight count, stable tie-break); session-bearing requests
  stick to their assigned replica via rendezvous (highest-random-weight)
  hashing, so replica-set churn only moves the sessions of the replica
  that changed;
* **eject / readmit** — every replica carries its own
  :class:`~sheeprl_tpu.resilience.retry.CircuitBreaker`: consecutive
  forward/probe failures open it (ejected — no traffic), the cool-down's
  half-open probe readmits it on the first success.  A background prober
  polls each replica's ``/healthz`` (the same surface the single-server
  deployment exposes, ``degraded``/``reload_breaker`` included);
* **failover** — a failed forward is retried on the next-best replica
  (``serve.fleet.route_retries`` distinct replicas) before the router
  answers 503 ``replica_unavailable`` — which the client retries, so a
  replica death costs latency, never a dropped request;
* **carry migration** — for stateful players the router mirrors each
  session's CRC-stamped latent carry (piggybacked on act responses);
  when a session's replica dies, the router replays the ``/v1/reset`` +
  ``/v1/session_carry`` rebuild contract onto the survivor it re-routes
  to, so the killed replica loses at most one in-flight step, never the
  session;
* **rolling reload** — a :class:`~sheeprl_tpu.serve.reload.CommitWatcher`
  (param "store" holding just the fleet's deployed step) walks replicas
  one at a time: drain → ``/v1/reload`` → verify → undrain.  Any failure
  halts the rollout with old params still serving everywhere, and the
  watcher's breaker/quarantine machinery (docs/resilience.md) takes over.

Chaos sites: ``serve.router`` fires at the router's own request handling,
``serve.replica`` fires on every router→replica leg (the knob drills use
to simulate replica kill/hang without touching the processes).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sheeprl_tpu.resilience.retry import CircuitBreaker


def assign_replica(session: str, rids: Sequence[str]) -> Optional[str]:
    """Rendezvous (highest-random-weight) hash: the replica id in ``rids``
    with the largest ``blake2b(session@rid)`` weight (a seeded digest, not
    Python's ``hash()`` — assignments must agree across processes and
    interpreter restarts).

    The property the fleet needs: removing one replica re-assigns ONLY the
    sessions that were on it (every other session's argmax is untouched),
    and adding one steals only the sessions whose new weight wins — no
    modulo-style global reshuffle on churn.
    """
    import hashlib

    if not rids:
        return None
    return max(
        sorted(rids),
        key=lambda rid: hashlib.blake2b(
            f"{session}@{rid}".encode(), digest_size=8
        ).digest(),
    )


class ReplicaState:
    """One replica as the router sees it: address, breaker, load."""

    def __init__(self, rid: str, url: str, eject_threshold: int = 3, readmit_s: float = 5.0):
        self.rid = rid
        self.url = url.rstrip("/")
        self.breaker = CircuitBreaker(
            failure_threshold=eject_threshold,
            reset_timeout_s=readmit_s,
            name=f"serve.fleet.{rid}",
        )
        self._lock = threading.Lock()
        self._inflight = 0
        #: router stopped sending traffic (rolling reload in progress)
        self.draining = False
        #: at least one successful /healthz since (re)registration — a
        #: replica is never routable before its first good probe
        self.probed = False
        self.last_health: Dict[str, Any] = {}

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def end(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def routable(self) -> bool:
        """May NEW traffic be sent here right now?"""
        return self.probed and not self.draining and self.breaker.allow()

    @property
    def checkpoint_step(self) -> int:
        return int(self.last_health.get("checkpoint_step", -1))

    def describe(self) -> Dict[str, Any]:
        return {
            "url": self.url,
            "routable": self.routable,
            "draining": self.draining,
            "probed": self.probed,
            "inflight": self.inflight,
            "breaker": self.breaker.snapshot(),
            "checkpoint_step": self.checkpoint_step,
            "degraded": bool(self.last_health.get("degraded", False)),
        }


class FleetRouter:
    """Health-checked, session-affine dispatch over a set of replicas.

    ``addresses`` maps stable replica ids (slot names like ``r0`` — a
    respawned process keeps its slot's id, so session assignments survive
    replica churn) to base URLs.  ``cfg`` is a composed run config whose
    ``serve.fleet`` group supplies the knobs; ``ckpt_root`` (optional)
    arms fleet-wide rolling hot reload on that run's commit stream.
    """

    def __init__(self, addresses: Dict[str, str], cfg: Any, ckpt_root: Optional[Any] = None):
        serve_cfg = (cfg.get("serve") or {}) if hasattr(cfg, "get") else {}
        fleet_cfg = serve_cfg.get("fleet") or {}
        self.cfg = cfg
        self.health_poll_s = float(fleet_cfg.get("health_poll_s", 1.0))
        self.health_timeout_s = float(fleet_cfg.get("health_timeout_s", 5.0))
        self.eject_threshold = int(fleet_cfg.get("eject_threshold", 3))
        self.readmit_s = float(fleet_cfg.get("readmit_s", 5.0))
        self.route_retries = max(1, int(fleet_cfg.get("route_retries", 3)))
        self.request_timeout_s = float(fleet_cfg.get("request_timeout_s", 60.0))
        self.drain_timeout_s = float(fleet_cfg.get("drain_timeout_s", 30.0))
        self.reload_poll_s = float(fleet_cfg.get("reload_poll_s", 2.0))
        self.carry_mirror = bool(fleet_cfg.get("carry_mirror", True))
        self._reload_failure_threshold = int(serve_cfg.get("reload_failure_threshold", 3))
        self._reload_breaker_reset_s = float(serve_cfg.get("reload_breaker_reset_s", 30.0))
        self._quarantine = bool(serve_cfg.get("quarantine_poisoned", True))
        self.ckpt_root = ckpt_root
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaState] = {}
        for rid, url in addresses.items():
            self._replicas[rid] = ReplicaState(
                rid, url, eject_threshold=self.eject_threshold, readmit_s=self.readmit_s
            )
        # session -> {"rid": ..., "carry": <snapshot|None>, "steps": n}
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._sessions_lock = threading.Lock()
        # fleet identity, learned from the first healthy probe
        self._spec: Optional[Dict[str, Any]] = None
        self.stateful = False
        self.watcher = None  # built in start() once the deployed step is known
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._started = False
        # counters (stats/metrics; guarded by _counters_lock)
        self._counters_lock = threading.Lock()
        self._routed = 0
        self._failovers = 0
        self._unroutable = 0
        self._ejects = 0
        self._readmits = 0
        self._migrations = 0
        self._rolling_reloads = 0
        self._reload_halts = 0
        self._replicas_reloaded = 0
        self._respawns = 0

    # -- replica-set management ----------------------------------------------
    def replica_list(self) -> List[ReplicaState]:
        with self._lock:
            return [self._replicas[rid] for rid in sorted(self._replicas)]

    def get_replica(self, rid: str) -> Optional[ReplicaState]:
        with self._lock:
            return self._replicas.get(rid)

    def mark_dead(self, rid: str) -> None:
        """The supervisor observed the process die: stop routing NOW
        instead of waiting for the breaker to accumulate probe failures."""
        rep = self.get_replica(rid)
        if rep is not None:
            rep.probed = False

    def replace_replica(self, rid: str, url: str) -> None:
        """A respawned process took over slot ``rid`` at a new address.
        Fresh breaker, unprobed (no traffic until the first good probe);
        the slot id is stable so rendezvous assignments keep their
        meaning."""
        with self._lock:
            self._replicas[rid] = ReplicaState(
                rid, url, eject_threshold=self.eject_threshold, readmit_s=self.readmit_s
            )

    def note_respawn(self) -> None:
        with self._counters_lock:
            self._respawns += 1

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._started:
            return self
        self._started = True
        for rep in self.replica_list():
            self._probe(rep)
        if self.ckpt_root is not None:
            from sheeprl_tpu.serve.reload import CommitWatcher, ParamStore

            # the fleet's "params" are just the deployed checkpoint step: the
            # watcher machinery (discovery, CRC verify, breaker, quarantine)
            # is reused verbatim, with _rollout_to as the load function —
            # a failed rollout is a failed load, poison is quarantined, and
            # the breaker's cool-down paces retries exactly like a single
            # server's reload path
            deployed = [r.checkpoint_step for r in self.replica_list() if r.probed]
            self._fleet_store = ParamStore(None, step=max(deployed) if deployed else -1)
            self.watcher = CommitWatcher(
                self.ckpt_root,
                self._fleet_store,
                self._rollout_to,
                poll_s=self.reload_poll_s,
                on_reload=self._note_rollout,
                failure_threshold=self._reload_failure_threshold,
                breaker_reset_s=self._reload_breaker_reset_s,
                quarantine=self._quarantine,
            )
            self.watcher.start()
        self._prober = threading.Thread(
            target=self._probe_loop, name="sheeprl-fleet-prober", daemon=True
        )
        self._prober.start()
        from sheeprl_tpu.telemetry import HUB

        HUB.register("fleet", self.hub_metrics)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.watcher is not None:
            self.watcher.stop()
        if self._prober is not None:
            self._prober.join(self.health_poll_s * 2 + 1.0)
        from sheeprl_tpu.telemetry import HUB

        HUB.unregister("fleet")
        self._started = False

    def wait_healthy(self, min_replicas: int = 1, timeout: float = 120.0) -> bool:
        """Block until ``min_replicas`` replicas are routable (startup
        barrier for the CLI/bench/tests)."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if sum(1 for r in self.replica_list() if r.routable) >= min_replicas:
                return True
            for rep in self.replica_list():
                if not rep.probed:
                    self._probe(rep)
            if self._stop.wait(0.25):
                return False
        return False

    # -- probing ---------------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.health_poll_s):
            for rep in self.replica_list():
                self._probe(rep)

    def _probe(self, rep: ReplicaState) -> bool:
        try:
            status, body = self._forward(
                rep, "GET", "/healthz", timeout=self.health_timeout_s
            )
            if status != 200 or not body.get("ok", False):
                raise IOError(f"healthz answered {status}")
        except Exception:
            self._note_failure(rep)
            return False
        rep.last_health = body
        rep.probed = True
        self._note_success(rep)
        if self._spec is None and body.get("obs_spec"):
            # fleet identity: every replica serves the same model, so the
            # first healthy answer defines the contract clients see
            self._spec = {
                "algo": body.get("algo"),
                "obs_spec": body.get("obs_spec"),
                "action_shape": body.get("action_shape"),
                "stateful": bool(body.get("stateful", False)),
            }
            self.stateful = self._spec["stateful"]
        return True

    def _note_failure(self, rep: ReplicaState) -> None:
        before = rep.breaker.state
        rep.breaker.record_failure()
        if before != CircuitBreaker.OPEN and rep.breaker.state == CircuitBreaker.OPEN:
            with self._counters_lock:
                self._ejects += 1

    def _note_success(self, rep: ReplicaState) -> None:
        before = rep.breaker.state
        rep.breaker.record_success()
        if before != CircuitBreaker.CLOSED:
            with self._counters_lock:
                self._readmits += 1

    # -- transport -------------------------------------------------------------
    def _forward(
        self,
        rep: ReplicaState,
        method: str,
        path: str,
        data: Optional[bytes] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        """One router→replica HTTP leg.  Connection-level failures raise;
        HTTP error statuses return ``(code, parsed-body)`` — the caller
        decides which are failover-worthy.  ``serve.replica`` is the chaos
        site on this leg: an injected raise/hang here looks exactly like a
        dead/wedged replica."""
        from sheeprl_tpu.resilience.faults import fault_point

        fault_point("serve.replica")
        req = urllib.request.Request(
            rep.url + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.request_timeout_s if timeout is None else timeout
            ) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raw = b""
            try:
                raw = e.read() or b""
            except Exception:
                pass
            try:
                payload = json.loads(raw)
            except Exception:
                payload = {"error": raw.decode("utf-8", "replace")[:512] or str(e)}
            return e.code, payload

    # -- dispatch --------------------------------------------------------------
    def _pick(self, session: Optional[str], tried: set) -> Optional[ReplicaState]:
        """The routing decision.  Sessions: the stored assignment while its
        replica lives, else rendezvous over the live set (lazy migration —
        a readmitted replica does NOT yank its old sessions back).
        Stateless: least in-flight, stable tie-break."""
        reps = self.replica_list()
        if session is not None:
            with self._sessions_lock:
                entry = self._sessions.get(session)
            if entry is not None and entry["rid"] not in tried:
                rep = self.get_replica(entry["rid"])
                # draining is temporary (rolling reload): keep the sticky
                # target, the act path waits the drain out
                if rep is not None and (rep.routable or (rep.probed and rep.draining)):
                    return rep
            cands = [r for r in reps if r.routable and r.rid not in tried]
            rid = assign_replica(session, [r.rid for r in cands])
            return next((r for r in cands if r.rid == rid), None)
        cands = [r for r in reps if r.routable and r.rid not in tried]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.inflight, r.rid))

    def _wait_not_draining(self, rep: ReplicaState, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while rep.draining:
            if time.monotonic() >= deadline or self._stop.is_set():
                return False
            time.sleep(0.02)
        return True

    def _place_session(self, session: str, rep: ReplicaState) -> None:
        """Bind ``session`` to ``rep``, replaying the mirrored carry when
        this is a migration (the old replica died mid-session).  The
        rebuild contract is exactly what a fresh client would do: /v1/reset
        to drop any stale state, then /v1/session_carry to install the
        last mirrored (pre-loss) latent carry.  Failures raise — the act
        loop treats them as a failed forward and fails over again."""
        with self._sessions_lock:
            entry = self._sessions.get(session)
            if entry is not None and entry["rid"] == rep.rid:
                return
            carry = entry.get("carry") if entry is not None else None
            migrating = entry is not None
        if migrating and self.stateful:
            body = json.dumps({"session": session}).encode()
            status, payload = self._forward(rep, "POST", "/v1/reset", body)
            if status != 200:
                raise IOError(f"migration reset answered {status}: {payload}")
            if carry is not None:
                body = json.dumps({"session": session, "snapshot": carry}).encode()
                status, payload = self._forward(rep, "POST", "/v1/session_carry", body)
                if status != 200:
                    raise IOError(f"carry restore answered {status}: {payload}")
            with self._counters_lock:
                self._migrations += 1
        with self._sessions_lock:
            self._sessions[session] = {"rid": rep.rid, "carry": carry}

    def act(self, raw: bytes) -> Tuple[int, Dict[str, Any]]:
        """Route one ``/v1/act`` body; returns ``(status, payload)``.

        ``serve.router`` is the chaos site at the router's own front door.
        The loop tries up to ``route_retries`` DISTINCT replicas; only
        requests that were provably never dispatched fail over (connection
        errors, 429 shed, 5xx from a replica that never batched it — the
        replica's own act path answers those before any carry advances), so
        a failover can never double-step a session.
        """
        from sheeprl_tpu.resilience.faults import fault_point

        fault_point("serve.router")
        try:
            body = json.loads(raw or b"{}")
        except Exception as e:
            return 400, {"error": f"invalid JSON body: {e}"}
        session = body.get("session")
        session = None if session is None else str(session)
        mirror = self.carry_mirror and self.stateful and session is not None
        if mirror and not body.get("return_carry"):
            body["return_carry"] = True
            raw = json.dumps(body).encode()
        tried: set = set()
        last_error: Optional[str] = None
        for _ in range(self.route_retries):
            rep = self._pick(session, tried)
            if rep is None:
                break
            if rep.draining and not self._wait_not_draining(rep, self.request_timeout_s):
                tried.add(rep.rid)
                last_error = f"replica {rep.rid} stuck draining"
                continue
            try:
                if session is not None:
                    self._place_session(session, rep)
                rep.begin()
                try:
                    status, payload = self._forward(rep, "POST", "/v1/act", raw)
                finally:
                    rep.end()
            except Exception as e:
                # connection refused/reset, timeout, injected serve.replica
                # fault: the replica never answered — fail over
                self._note_failure(rep)
                tried.add(rep.rid)
                last_error = f"{type(e).__name__}: {e}"
                with self._counters_lock:
                    self._failovers += 1
                continue
            if status < 400:
                self._note_success(rep)
                if mirror and "carry" in payload:
                    snapshot = payload.pop("carry")
                    with self._sessions_lock:
                        entry = self._sessions.get(session)
                        if entry is not None and entry["rid"] == rep.rid:
                            entry["carry"] = snapshot
                with self._counters_lock:
                    self._routed += 1
                payload["replica"] = rep.rid
                return status, payload
            if status == 429 or status >= 500:
                # 429: the replica shed the request before dispatch; 5xx:
                # its act path failed before resolving — either way the
                # request never advanced a carry, so another replica may
                # serve it.  Only 5xx is breaker evidence (429 is load, not
                # illness).
                if status >= 500:
                    self._note_failure(rep)
                tried.add(rep.rid)
                last_error = f"replica {rep.rid} answered {status}: {payload.get('error')}"
                with self._counters_lock:
                    self._failovers += 1
                continue
            return status, payload  # other 4xx: the request itself is bad
        with self._counters_lock:
            self._unroutable += 1
        return 503, {
            "error": "replica_unavailable: no routable replica "
            f"(tried {sorted(tried) or 'none'}; last: {last_error})"
        }

    def reset(self, session: str) -> Tuple[int, Dict[str, Any]]:
        """Drop a session fleet-wide: the router's assignment + mirror, and
        the assigned replica's carry (best-effort — a dead replica took its
        carry with it anyway)."""
        with self._sessions_lock:
            entry = self._sessions.pop(session, None)
        if entry is not None:
            rep = self.get_replica(entry["rid"])
            if rep is not None and rep.probed:
                try:
                    self._forward(
                        rep, "POST", "/v1/reset", json.dumps({"session": session}).encode()
                    )
                except Exception:
                    pass
        return 200, {"ok": True}

    # -- rolling reload --------------------------------------------------------
    def reload_once(self) -> Tuple[int, Dict[str, Any]]:
        """Force one commit-watch poll (the fleet spelling of
        ``POST /v1/reload``)."""
        if self.watcher is None:
            return 200, {"reloaded": False, "error": "rolling reload disabled (no ckpt_root)"}
        gen = self.watcher.poll_once()
        return 200, {
            "reloaded": gen is not None,
            "generation": self._fleet_store.generation,
            "fleet_step": self._fleet_store.step,
            "degraded": self.watcher.degraded,
        }

    def _note_rollout(self, generation: int, step: int) -> None:
        with self._counters_lock:
            self._rolling_reloads += 1
        print(f"[fleet] rolling reload complete: step {step} (generation {generation})", flush=True)

    def _rollout_to(self, step_dir: Any) -> int:
        """The CommitWatcher ``load_params`` hook: roll ``step_dir`` out
        replica by replica.  Raises on the FIRST failure — remaining
        replicas are never touched, old params keep serving everywhere,
        and the watcher's breaker/quarantine handles the poison."""
        from sheeprl_tpu.checkpoint.protocol import checkpoint_step

        step = checkpoint_step(step_dir)
        try:
            for rep in self.replica_list():
                if not rep.probed:
                    # dead/respawning slot: the supervisor's respawn loads
                    # the newest commit on its own, skip it here
                    continue
                rep.draining = True
                try:
                    deadline = time.monotonic() + self.drain_timeout_s
                    while rep.inflight > 0:
                        if time.monotonic() >= deadline:
                            raise TimeoutError(
                                f"replica {rep.rid} still has {rep.inflight} in-flight "
                                f"requests after {self.drain_timeout_s}s drain"
                            )
                        time.sleep(0.02)
                    status, payload = self._forward(
                        rep,
                        "POST",
                        "/v1/reload",
                        b"{}",
                        timeout=max(self.request_timeout_s, 120.0),
                    )
                    if status != 200:
                        raise IOError(f"replica {rep.rid} reload answered {status}: {payload}")
                    if int(payload.get("checkpoint_step", -1)) != step:
                        raise IOError(
                            f"replica {rep.rid} is at step {payload.get('checkpoint_step')} "
                            f"after reload, wanted {step} (its own reload breaker likely "
                            "opened — see its /healthz)"
                        )
                finally:
                    rep.draining = False
                with self._counters_lock:
                    self._replicas_reloaded += 1
        except Exception:
            with self._counters_lock:
                self._reload_halts += 1
            raise
        return step

    # -- observability ---------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        reps = self.replica_list()
        healthy = sum(1 for r in reps if r.routable)
        out: Dict[str, Any] = {
            "ok": healthy > 0,
            "fleet": True,
            "replicas": len(reps),
            "healthy": healthy,
            "draining": sum(1 for r in reps if r.draining),
            "stateful": self.stateful,
            "degraded": self.watcher.degraded if self.watcher is not None else False,
            "reload_breaker": (
                self.watcher.breaker.snapshot() if self.watcher is not None else None
            ),
            "fleet_step": (
                self._fleet_store.step
                if self.watcher is not None
                else max([r.checkpoint_step for r in reps if r.probed], default=-1)
            ),
            "per_replica": {r.rid: r.describe() for r in reps},
        }
        if self._spec is not None:
            # the single-server /healthz contract (obs_spec, action_shape,
            # algo): clients talk to the fleet exactly like one server
            out.update(self._spec)
        return out

    def stats(self) -> Dict[str, Any]:
        with self._counters_lock:
            counters = {
                "routed": self._routed,
                "failovers": self._failovers,
                "unroutable": self._unroutable,
                "ejects": self._ejects,
                "readmits": self._readmits,
                "migrations": self._migrations,
                "rolling_reloads": self._rolling_reloads,
                "reload_halts": self._reload_halts,
                "replicas_reloaded": self._replicas_reloaded,
                "respawns": self._respawns,
            }
        with self._sessions_lock:
            sessions = len(self._sessions)
        out = dict(self.health())
        out.pop("per_replica", None)
        out.update(counters)
        out["sessions"] = sessions
        out["per_replica"] = {r.rid: r.describe() for r in self.replica_list()}
        return out

    def hub_metrics(self) -> Dict[str, float]:
        """``Fleet/*`` telemetry-hub family (registered on :meth:`start`,
        exported on the router's ``/metrics`` like every other source)."""
        s = self.stats()
        metrics: Dict[str, float] = {}
        for key in (
            "replicas", "healthy", "draining", "routed", "failovers",
            "unroutable", "ejects", "readmits", "migrations", "sessions",
            "rolling_reloads", "reload_halts", "replicas_reloaded",
            "respawns", "fleet_step",
        ):
            value = s.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                metrics[f"Fleet/{key}"] = float(value)
        metrics["Fleet/degraded"] = 1.0 if s.get("degraded") else 0.0
        return metrics


class FleetServer:
    """Stdlib HTTP front over a :class:`FleetRouter` — the one address
    clients see.  Speaks the same protocol as ``serve/server.py``, so
    :class:`~sheeprl_tpu.serve.client.PolicyClient` points at a fleet
    unchanged."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1", port: int = 0):
        from http.server import ThreadingHTTPServer

        class _FrontHTTPServer(ThreadingHTTPServer):
            # the fleet front absorbs every client's connection-per-request
            # burst; the stdlib default backlog of 5 RSTs connections under
            # concurrent load
            request_queue_size = 128

        self.router = router
        self._httpd = _FrontHTTPServer((host, port), _make_handler(router))
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "FleetServer":
        self.router.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="sheeprl-fleet-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
        self.router.stop()

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def serve_forever(self) -> None:
        """Foreground loop for the CLI entry (Ctrl-C → clean shutdown)."""
        self.router.start()
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self._httpd.server_close()
            self.router.stop()


def _make_handler(router: FleetRouter):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args: Any) -> None:  # quiet by default
            pass

        def _reply(self, code: int, payload: Dict[str, Any]) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_raw(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length) if length > 0 else b""

        def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
            try:
                from sheeprl_tpu.resilience.faults import fault_point

                fault_point("serve.router")
                if self.path == "/healthz":
                    body = router.health()
                    self._reply(200 if body["ok"] else 503, body)
                elif self.path == "/v1/stats":
                    self._reply(200, router.stats())
                elif self.path == "/metrics":
                    from sheeprl_tpu.telemetry import (
                        HUB,
                        PROMETHEUS_CONTENT_TYPE,
                        prometheus_text,
                    )

                    body = prometheus_text(HUB.collect()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except BrokenPipeError:
                pass
            except Exception as e:
                self._safe_error(500, e)

        def do_POST(self) -> None:  # noqa: N802
            try:
                if self.path == "/v1/act":
                    code, payload = router.act(self._read_raw())
                elif self.path == "/v1/reset":
                    from sheeprl_tpu.resilience.faults import fault_point

                    fault_point("serve.router")
                    body = json.loads(self._read_raw() or b"{}")
                    code, payload = router.reset(str(body.get("session", "")))
                elif self.path == "/v1/reload":
                    code, payload = router.reload_once()
                else:
                    code, payload = 404, {"error": f"unknown path {self.path}"}
                self._reply(code, payload)
            except BrokenPipeError:
                pass
            except Exception as e:
                self._safe_error(500, e)

        def _safe_error(self, code: int, e: Exception) -> None:
            try:
                self._reply(code, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    return Handler
