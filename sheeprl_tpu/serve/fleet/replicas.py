"""Local replica supervisor: spawn N policy servers, respawn the dead.

The process-management half of the fleet (the PR 14 run supervisor's
pattern, scoped to serving): each replica slot (``r0``..``rN-1``) runs one
``python -m sheeprl_tpu.serve`` child on an ephemeral port with commit
watching OFF (the router owns rollout ordering — a replica that watched
commits itself would break the drain-one-at-a-time contract).  A monitor
thread notices dead children, tells the router to stop routing to the slot
immediately (:meth:`FleetRouter.mark_dead`), and respawns with jittered
exponential backoff under a fleet-lifetime budget; the respawned process
keeps the SLOT id (stable rendezvous assignments) at whatever new address
it binds.

A respawned replica re-resolves ``checkpoint_path`` itself — pass a
run/version directory (→ newest committed snapshot), not a pinned
``step_*`` dir, or respawns will come back serving stale params after a
rolling reload.
"""

from __future__ import annotations

import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

#: the line ``sheeprl_tpu.cli:serve`` prints once its socket is bound
_URL_RE = re.compile(r" on (http://[\d.]+:\d+)")


class _Slot:
    """One replica slot: a stable id over a sequence of child processes."""

    def __init__(self, index: int):
        self.index = index
        self.rid = f"r{index}"
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.url_event = threading.Event()
        self.respawns = 0


class LocalFleet:
    """Spawn/supervise N local serve processes for a :class:`FleetRouter`.

    ``checkpoint_path`` plus ``overrides`` become each child's CLI
    arguments; ``serve.port=0`` and ``serve.watch_commits=false`` are
    appended last (they must win).  ``child_cmd`` / ``child_env`` exist
    for tests (swap the interpreter invocation, force ``JAX_PLATFORMS``).
    """

    def __init__(
        self,
        checkpoint_path: str,
        overrides: Sequence[str] = (),
        replicas: int = 2,
        respawn_max: int = 10,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        spawn_timeout_s: float = 600.0,
        child_cmd: Optional[Callable[[List[str]], List[str]]] = None,
        child_env: Optional[Dict[str, str]] = None,
        seed: int = 0,
        echo: bool = True,
    ):
        self.checkpoint_path = str(checkpoint_path)
        self.overrides = [a for a in overrides if not a.startswith("checkpoint_path=")]
        self.n = max(1, int(replicas))
        self.respawn_max = int(respawn_max)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._child_cmd = child_cmd or (
            lambda argv: [sys.executable, "-m", "sheeprl_tpu.serve", *argv]
        )
        self._child_env = dict(child_env) if child_env else None
        self._rng = random.Random(int(seed) or None)
        self._echo = bool(echo)
        self._slots = [_Slot(i) for i in range(self.n)]
        self._router: Optional[Any] = None
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.respawns_used = 0

    # -- spawning --------------------------------------------------------------
    def _child_argv(self) -> List[str]:
        return [
            f"checkpoint_path={self.checkpoint_path}",
            *self.overrides,
            # appended LAST so they win: every replica on its own ephemeral
            # port, commit watch off (the router's rolling reload is the
            # only thing allowed to move a replica's params)
            "serve.port=0",
            "serve.watch_commits=false",
        ]

    def _spawn(self, slot: _Slot) -> None:
        env = None
        if self._child_env is not None:
            env = {**os.environ, **self._child_env}
        slot.url = None
        slot.url_event.clear()
        proc = subprocess.Popen(
            self._child_cmd(self._child_argv()),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        slot.proc = proc

        def drain() -> None:
            try:
                for line in proc.stdout:  # type: ignore[union-attr]
                    if self._echo:
                        sys.stdout.write(f"[{slot.rid}] {line}")
                        sys.stdout.flush()
                    if slot.url is None:
                        m = _URL_RE.search(line)
                        if m:
                            slot.url = m.group(1)
                            slot.url_event.set()
            except (ValueError, OSError):
                pass  # pipe closed under us during kill

        threading.Thread(target=drain, name=f"fleet-stdout-{slot.rid}", daemon=True).start()

    def _wait_url(self, slot: _Slot, timeout: float) -> Optional[str]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            if slot.url_event.wait(0.5):
                return slot.url
            if slot.proc is not None and slot.proc.poll() is not None:
                return None  # died before binding
        return None

    def start(self) -> "LocalFleet":
        """Spawn every slot and block until each has printed its URL.
        Children warm their batch ladders concurrently — the slowest one
        bounds startup, not the sum."""
        for slot in self._slots:
            self._spawn(slot)
        for slot in self._slots:
            if self._wait_url(slot, self.spawn_timeout_s) is None:
                self.stop()
                raise RuntimeError(
                    f"replica {slot.rid} failed to start within {self.spawn_timeout_s}s"
                )
        return self

    def addresses(self) -> Dict[str, str]:
        return {slot.rid: slot.url for slot in self._slots if slot.url}

    # -- supervision -----------------------------------------------------------
    def attach(self, router: Any) -> None:
        """Wire the respawn loop to a router and start monitoring."""
        self._router = router
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()

    def _backoff_s(self, slot: _Slot) -> float:
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * (2.0 ** max(0, slot.respawns - 1)),
        )
        return base * self._rng.uniform(0.5, 1.5)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.5):
            for slot in self._slots:
                proc = slot.proc
                if proc is None or proc.poll() is None:
                    continue
                rc = proc.returncode
                if self._router is not None:
                    self._router.mark_dead(slot.rid)
                if self.respawns_used >= self.respawn_max:
                    print(
                        f"[fleet] replica {slot.rid} died (rc={rc}) — respawn budget "
                        f"exhausted ({self.respawn_max}), slot stays down",
                        flush=True,
                    )
                    slot.proc = None
                    continue
                self.respawns_used += 1
                slot.respawns += 1
                delay = self._backoff_s(slot)
                print(
                    f"[fleet] replica {slot.rid} died (rc={rc}) — respawning in "
                    f"{delay:.1f}s ({self.respawns_used}/{self.respawn_max})",
                    flush=True,
                )
                if self._stop.wait(delay):
                    return
                self._spawn(slot)
                url = self._wait_url(slot, self.spawn_timeout_s)
                if url is None:
                    # died again before binding: next loop pass classifies it
                    continue
                if self._router is not None:
                    self._router.replace_replica(slot.rid, url)
                    self._router.note_respawn()
                print(f"[fleet] replica {slot.rid} back at {url}", flush=True)

    # -- chaos / teardown ------------------------------------------------------
    def kill(self, index: int, sig: int = signal.SIGKILL) -> None:
        """Kill one replica process (the chaos drill's hammer).  The
        monitor notices and runs the ordinary respawn path — that's the
        point: a drill kill and a real crash share every line of code."""
        slot = self._slots[index]
        if slot.proc is not None and slot.proc.poll() is None:
            slot.proc.send_signal(sig)

    def stop(self, timeout: float = 15.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        for slot in self._slots:
            proc = slot.proc
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.send_signal(signal.SIGINT)  # serve_forever's clean path
            except OSError:
                continue
        deadline = time.monotonic() + timeout
        for slot in self._slots:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                    proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    def __enter__(self) -> "LocalFleet":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
