"""Per-algorithm policy players for serving.

A :class:`PolicyPlayer` is the serving-side view of a trained agent: the
minimal parameter subtree, a host-side observation prepare step, and ONE
jitted step program ``(params, carry, obs, seed, greedy) -> (carry, action)``
wrapped in :class:`~sheeprl_tpu.parallel.compile.AOTFunction` so it can be
AOT-compiled at a fixed ladder of batch sizes and never recompile in steady
state.

Design constraints that shape the step signature:

* ``greedy`` is a per-row ``bool`` ARRAY, not a static flag — a coalesced
  batch may mix greedy and sampling requests, and making the flag dynamic
  keeps it to one executable per batch size (both branches are computed and
  row-selected; XLA shares the common prefix, and the extra sample is noise
  next to the network forward).
* ``seed`` is a dynamic ``int32`` scalar: the key is derived inside the
  program (``jax.random.PRNGKey(seed)``), so the host just increments a
  counter and no per-dispatch device key plumbing can perturb the abstract
  signature.
* ``carry`` is ``()`` for stateless players (ppo, sac) and the latent-state
  tuple for dreamer_v3; per-session carries are scattered/gathered by the
  batcher on the host.

The same players back ``sheeprl_tpu.cli:evaluation`` (via ``serve.loader``),
so evaluation and serving can never disagree on how a snapshot is
reconstructed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from sheeprl_tpu.parallel.compile import AOTFunction

PLAYER_BUILDERS: Dict[str, Callable] = {}


def register_player(*algo_names: str) -> Callable:
    """Class/function decorator registering a player builder for algo names.

    A builder has signature
    ``(fabric, cfg, state, obs_space, action_space) -> PolicyPlayer``.
    """

    def deco(fn: Callable) -> Callable:
        for name in algo_names:
            PLAYER_BUILDERS[name] = fn
        return fn

    return deco


@dataclass
class PolicyPlayer:
    """Serving-side policy: prepare → step (AOT) → postprocess.

    ``step`` maps ``(params, carry, prepared_obs, seed, greedy_mask)`` to
    ``(new_carry, actions)`` where ``actions`` are already env-shaped on the
    device side (discrete → float branch indices); ``postprocess`` finishes
    the host-side conversion (int casts, bound rescaling).
    """

    algo: str
    params: Any  # device-resident player parameter subtree
    step: AOTFunction
    prepare: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]
    postprocess: Callable[[np.ndarray], np.ndarray]
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]]  # raw per-request spec
    action_shape: Tuple[int, ...]  # per-request env action shape
    is_continuous: bool
    actions_dim: Tuple[int, ...]
    stateful: bool = False
    carry_spec: Tuple[Tuple[Tuple[int, ...], str], ...] = ()  # per-row leaves
    checkpoint_step: int = -1

    # -- carry handling (host side; per-row leaves have leading dim 1) ------
    def zero_carry(self, batch: int) -> Tuple[np.ndarray, ...]:
        return tuple(
            np.zeros((batch, *shape), dtype=np.dtype(dt)) for shape, dt in self.carry_spec
        )

    def zero_carry_row(self) -> Tuple[np.ndarray, ...]:
        return self.zero_carry(1)

    # -- batched dispatch ----------------------------------------------------
    def step_batch(
        self,
        params: Any,
        carry: Tuple[np.ndarray, ...],
        obs: Dict[str, np.ndarray],
        seed: int,
        greedy: np.ndarray,
    ) -> Tuple[Tuple[np.ndarray, ...], np.ndarray]:
        """One batched policy step.  ``obs`` must already be prepared and
        padded to a ladder batch size; returns host arrays."""
        new_carry, actions = self.step(
            params, carry, obs, np.int32(seed), np.asarray(greedy, bool)
        )
        new_carry = tuple(np.asarray(c) for c in new_carry)
        return new_carry, np.asarray(actions)

    # -- warm-up -------------------------------------------------------------
    def batch_specs(self, batch: int) -> Tuple[Any, ...]:
        """``(params, carry, obs, seed, greedy)`` arguments for warming
        ladder batch size ``batch``.  Params are the REAL device arrays
        (their placement is part of the abstract signature); everything else
        is concrete zero-filled HOST arrays — the same leaf kind
        (``np.ndarray``) the dispatcher passes, so the warm-up lands in
        exactly the cache slot steady-state dispatch will hit."""
        obs_spec = {
            k: np.zeros((batch, *shape), np.dtype(dt))
            for k, (shape, dt) in self._prep_spec.items()
        }
        return (
            self.params,
            self.zero_carry(batch),
            obs_spec,
            np.int32(0),
            np.zeros((batch,), bool),
        )

    # prepared-obs per-row spec, derived once from a zero probe batch
    _prep_spec: Dict[str, Tuple[Tuple[int, ...], str]] = field(default_factory=dict)

    def finalize(self) -> "PolicyPlayer":
        """Derive the prepared-observation spec from a size-1 zero batch."""
        probe = {
            k: np.zeros((1, *shape), dtype=np.dtype(dt)) for k, (shape, dt) in self.obs_spec.items()
        }
        prepped = self.prepare(probe)
        self._prep_spec = {
            k: (tuple(np.asarray(v).shape[1:]), str(np.asarray(v).dtype))
            for k, v in prepped.items()
        }
        return self


def _split_branches(a: np.ndarray, actions_dim: Sequence[int]) -> np.ndarray:
    """One-hot concat (B, sum(dims)) → float branch indices (B, n_branches)."""
    idx, start = [], 0
    for d in actions_dim:
        idx.append(np.argmax(a[..., start : start + d], axis=-1))
        start += d
    return np.stack(idx, axis=-1).astype(np.float32)


def _obs_spec_from_space(obs_space: Any, keys: Sequence[str]) -> Dict[str, Any]:
    return {k: (tuple(obs_space[k].shape), str(obs_space[k].dtype)) for k in keys}


# ---------------------------------------------------------------------------
# PPO family
# ---------------------------------------------------------------------------


@register_player("ppo", "ppo_decoupled")
def build_ppo_player(fabric: Any, cfg: Any, state: Dict[str, Any], obs_space: Any, action_space: Any) -> PolicyPlayer:
    from sheeprl_tpu.algos.ppo.agent import build_agent, sample_actions
    from sheeprl_tpu.algos.ppo.utils import actions_for_env, obs_to_np, spaces_to_dims

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    actions_dim, is_continuous = spaces_to_dims(action_space)
    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space, state["agent"]
    )
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    def _step(p, carry, obs, seed, greedy):
        key = jax.random.PRNGKey(seed)
        out, _ = agent.apply(p, obs)
        a_sample, _, _ = sample_actions(
            out, actions_dim, is_continuous, key, greedy=False, dist_type=dist_type
        )
        # the greedy arm takes mode(), never drawing from `key` — the dual-arm
        # per-row select is ONE real consumer of the stream
        a_greedy, _, _ = sample_actions(  # graftlint: disable=prng-key-reuse
            out, actions_dim, is_continuous, key, greedy=True, dist_type=dist_type
        )
        return carry, jnp.where(greedy[:, None], a_greedy, a_sample)

    def prepare(obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {k: obs_to_np(obs[k], is_image=True) for k in cnn_keys}
        out.update({k: obs_to_np(obs[k], is_image=False) for k in mlp_keys})
        return out

    return PolicyPlayer(
        algo=cfg.algo.name,
        params=params,
        step=fabric.compile(_step, name=f"serve_step:{cfg.algo.name}"),
        prepare=prepare,
        postprocess=lambda a: actions_for_env(a, action_space),
        obs_spec=_obs_spec_from_space(obs_space, cnn_keys + mlp_keys),
        action_shape=tuple(np.shape(action_space.sample())),
        is_continuous=is_continuous,
        actions_dim=tuple(actions_dim),
    ).finalize()


# ---------------------------------------------------------------------------
# SAC family
# ---------------------------------------------------------------------------


@register_player("sac", "sac_decoupled")
def build_sac_player(fabric: Any, cfg: Any, state: Dict[str, Any], obs_space: Any, action_space: Any) -> PolicyPlayer:
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.utils.distribution import TanhNormal

    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(action_space.shape))
    actor, _, params = build_agent(fabric, act_dim, cfg, obs_dim, state["agent"])
    # serving only needs the actor subtree — the critics stay on the host
    actor_params = fabric.replicate({"actor": params["actor"]})

    def _step(p, carry, obs, seed, greedy):
        key = jax.random.PRNGKey(seed)
        mean, log_std = actor.apply(p["actor"], obs["__sac_obs__"])
        dist = TanhNormal(mean, jnp.exp(log_std))
        a = jnp.where(greedy[:, None], dist.mode(), dist.sample(key))
        return carry, a

    def prepare(obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        parts = [
            np.asarray(obs[k], np.float32).reshape(np.asarray(obs[k]).shape[0], -1)
            for k in mlp_keys
        ]
        return {"__sac_obs__": np.concatenate(parts, axis=-1)}

    low = np.asarray(action_space.low, np.float32)
    high = np.asarray(action_space.high, np.float32)

    def postprocess(a: np.ndarray) -> np.ndarray:
        # actor outputs [-1, 1]; rescale to the env's bounds (sac.utils.test)
        return low + (np.asarray(a, np.float32) + 1.0) * 0.5 * (high - low)

    return PolicyPlayer(
        algo=cfg.algo.name,
        params=actor_params,
        step=fabric.compile(_step, name=f"serve_step:{cfg.algo.name}"),
        prepare=prepare,
        postprocess=postprocess,
        obs_spec=_obs_spec_from_space(obs_space, mlp_keys),
        action_shape=tuple(action_space.shape),
        is_continuous=True,
        actions_dim=(act_dim,),
    ).finalize()


# ---------------------------------------------------------------------------
# DreamerV3
# ---------------------------------------------------------------------------


@register_player("dreamer_v3")
def build_dreamer_v3_player(fabric: Any, cfg: Any, state: Dict[str, Any], obs_space: Any, action_space: Any) -> PolicyPlayer:
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.ppo.utils import actions_for_env, spaces_to_dims
    from sheeprl_tpu.utils.utils import merge_framestack

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    actions_dim, is_continuous = spaces_to_dims(action_space)
    world_model, actor, _, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space, state["agent"]
    )
    WM = type(world_model)
    act_width = int(sum(actions_dim))
    rec_size = int(cfg.algo.world_model.recurrent_model.recurrent_state_size)
    stoch_flat = int(world_model.stoch_flat)
    player_params = fabric.replicate(
        {"world_model": params["world_model"], "actor": params["actor"]}
    )

    def _step(p, carry, obs, seed, greedy):
        h, z, prev_a = carry
        key = jax.random.PRNGKey(seed)
        k_repr, k_act = jax.random.split(key)
        embed = world_model.apply(p["world_model"], obs, method=WM.encode)
        h, z, _, _ = world_model.apply(
            p["world_model"], h, z, prev_a, embed,
            jnp.zeros((h.shape[0], 1)), k_repr, method=WM.dynamic,
        )
        latent = jnp.concatenate([z, h], -1)
        out = actor.apply(p["actor"], latent)
        a = jnp.where(
            greedy[:, None],
            # greedy arm takes mode() and never draws from k_act: the dual-arm
            # select has ONE real consumer of the stream
            actor.sample(out, k_act, greedy=True),
            actor.sample(out, k_act, greedy=False),  # graftlint: disable=prng-key-reuse
        )
        return (h, z, a), a

    def prepare(obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for k in cnn_keys:
            x = np.asarray(obs[k])
            if x.ndim == 5:  # (B, S, H, W, C) frame stack → channels
                x = merge_framestack(x)
            out[k] = np.asarray(x, np.float32) / 255.0 - 0.5
        for k in mlp_keys:
            x = np.asarray(obs[k], np.float32)
            out[k] = x.reshape(x.shape[0], -1)
        return out

    def postprocess(a: np.ndarray) -> np.ndarray:
        if not is_continuous:
            a = _split_branches(a, actions_dim)
        return actions_for_env(a, action_space)

    return PolicyPlayer(
        algo=cfg.algo.name,
        params=player_params,
        step=fabric.compile(_step, name=f"serve_step:{cfg.algo.name}"),
        prepare=prepare,
        postprocess=postprocess,
        obs_spec=_obs_spec_from_space(obs_space, cnn_keys + mlp_keys),
        action_shape=tuple(np.shape(action_space.sample())),
        is_continuous=is_continuous,
        actions_dim=tuple(actions_dim),
        stateful=True,
        carry_spec=(
            ((rec_size,), "float32"),
            ((stoch_flat,), "float32"),
            ((act_width,), "float32"),
        ),
    ).finalize()


def extract_player_state(player: PolicyPlayer, fabric: Any, agent_state: Dict[str, Any]) -> Any:
    """The player-relevant device subtree of a freshly-loaded ``agent``
    checkpoint entry — the hot-reload twin of what each builder put in
    ``player.params`` (double-buffered: this allocates NEW device buffers
    while the old ones keep serving)."""
    if player.algo.startswith("sac"):
        return fabric.replicate({"actor": agent_state["actor"]})
    if player.algo == "dreamer_v3":
        return fabric.replicate(
            {"world_model": agent_state["world_model"], "actor": agent_state["actor"]}
        )
    return fabric.replicate(agent_state)
