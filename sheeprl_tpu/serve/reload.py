"""Hot checkpoint reload: COMMIT watcher + double-buffered param swap.

:class:`ParamStore` owns the device parameter subtree the dispatcher reads;
:class:`CommitWatcher` polls the run's checkpoint directory for a newer
``COMMIT`` marker (``checkpoint.protocol.newer_checkpoint``), loads the new
shard on its OWN thread, transfers it host→device into FRESH buffers while
the old ones keep serving (double buffering), and then swaps the store's
pointer under a lock.

In-flight requests are never dropped: a dispatch captures the params
reference once at batch start, so a swap mid-batch only affects the NEXT
batch.  Shapes/dtypes/placement of the new tree are identical to the old
one (same agent, same fabric), so the warmed executables accept it without
recompiling.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import jax


class ParamStore:
    """Versioned, thread-safe pointer to the serving parameter subtree."""

    def __init__(self, params: Any, step: int = -1):
        self._lock = threading.Lock()
        self._params = params
        self._generation = 0
        self._step = int(step)

    def get(self) -> Any:
        with self._lock:
            return self._params

    def snapshot(self) -> tuple:
        """(params, generation, checkpoint_step) under one lock hold."""
        with self._lock:
            return self._params, self._generation, self._step

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    def swap(self, params: Any, step: int) -> int:
        """Install a new (already device-resident) tree; returns the new
        generation.  The old tree stays alive until every in-flight dispatch
        holding its reference finishes — garbage collection IS the second
        half of the double buffer."""
        with self._lock:
            self._params = params
            self._step = int(step)
            self._generation += 1
            return self._generation


class CommitWatcher:
    """Background thread hot-swapping params on every new ``COMMIT``."""

    def __init__(
        self,
        ckpt_root: Any,
        store: ParamStore,
        load_params: Callable[[Any], Any],
        poll_s: float = 2.0,
        on_reload: Optional[Callable[[int, int], None]] = None,
    ):
        """``load_params(step_dir) -> device tree`` does the rank-shard read
        + host→device transfer (built by the service from the player's
        extract rule); ``on_reload(generation, step)`` is a notification
        hook (stats, logs)."""
        self._ckpt_root = ckpt_root
        self._store = store
        self._load_params = load_params
        self._poll_s = float(poll_s)
        self._on_reload = on_reload
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._poll_lock = threading.Lock()
        self.reloads = 0
        self.last_error: Optional[str] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="sheeprl-serve-reload", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def poll_once(self) -> Optional[int]:
        """One synchronous check (also used by the HTTP ``/v1/reload``
        endpoint and tests): swap if a newer commit exists, return the new
        generation or None.  Serialized: a concurrent poll (watcher thread +
        ``/v1/reload`` handler) could otherwise finish a SLOW load of step N
        after a faster poll already swapped to N+1 and roll the server back
        to stale params — the lock makes every check-load-swap atomic, and
        the entry check rereads ``store.step`` so the loser just no-ops."""
        from sheeprl_tpu.checkpoint.protocol import checkpoint_step, newer_checkpoint

        with self._poll_lock:
            found = newer_checkpoint(self._ckpt_root, self._store.step)
            if found is None:
                return None
            try:
                new_params = self._load_params(found)
                # the transfer above allocated fresh device buffers; fence it
                # so the swap publishes a fully-materialized tree
                for leaf in jax.tree_util.tree_leaves(new_params):
                    if hasattr(leaf, "block_until_ready"):
                        leaf.block_until_ready()
            except Exception as e:  # a torn read mid-GC, OOM, … — keep serving
                self.last_error = f"{type(e).__name__}: {e}"
                return None
            gen = self._store.swap(new_params, checkpoint_step(found))
            self.reloads += 1
            self.last_error = None
            if self._on_reload is not None:
                self._on_reload(gen, self._store.step)
            return gen

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # never let the watcher die silently
                self.last_error = f"{type(e).__name__}: {e}"
            self._stop.wait(self._poll_s)
