"""Hot checkpoint reload: COMMIT watcher + double-buffered param swap.

:class:`ParamStore` owns the device parameter subtree the dispatcher reads;
:class:`CommitWatcher` polls the run's checkpoint directory for a newer
``COMMIT`` marker (``checkpoint.protocol.newer_checkpoint``), loads the new
shard on its OWN thread, transfers it host→device into FRESH buffers while
the old ones keep serving (double buffering), and then swaps the store's
pointer under a lock.

In-flight requests are never dropped: a dispatch captures the params
reference once at batch start, so a swap mid-batch only affects the NEXT
batch.  Shapes/dtypes/placement of the new tree are identical to the old
one (same agent, same fabric), so the warmed executables accept it without
recompiling.

Failure containment (the resilience layer, docs/resilience.md): a load
failure NEVER interrupts serving — the store keeps the old params.  A
:class:`~sheeprl_tpu.resilience.retry.CircuitBreaker` counts consecutive
failures; after ``failure_threshold`` failed loads of the SAME snapshot
that snapshot is declared poisoned and QUARANTINED
(``checkpoint.protocol.quarantine_checkpoint`` → ``step_*.corrupt``), so
discovery moves on to the next commit instead of hammering a corrupt
directory forever.  While the breaker is open the watcher skips load
attempts for its cool-down; breaker state is surfaced in ``/healthz``
(``degraded: true``) and ``/v1/stats``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax

from sheeprl_tpu.resilience.retry import CircuitBreaker


class ParamStore:
    """Versioned, thread-safe pointer to the serving parameter subtree."""

    def __init__(self, params: Any, step: int = -1):
        self._lock = threading.Lock()
        self._params = params
        self._generation = 0
        self._step = int(step)

    def get(self) -> Any:
        with self._lock:
            return self._params

    def snapshot(self) -> tuple:
        """(params, generation, checkpoint_step) under one lock hold."""
        with self._lock:
            return self._params, self._generation, self._step

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    def swap(self, params: Any, step: int) -> int:
        """Install a new (already device-resident) tree; returns the new
        generation.  The old tree stays alive until every in-flight dispatch
        holding its reference finishes — garbage collection IS the second
        half of the double buffer."""
        with self._lock:
            self._params = params
            self._step = int(step)
            self._generation += 1
            return self._generation


class CommitWatcher:
    """Background thread hot-swapping params on every new ``COMMIT``."""

    def __init__(
        self,
        ckpt_root: Any,
        store: ParamStore,
        load_params: Callable[[Any], Any],
        poll_s: float = 2.0,
        on_reload: Optional[Callable[[int, int], None]] = None,
        failure_threshold: int = 3,
        breaker_reset_s: float = 30.0,
        quarantine: bool = True,
    ):
        """``load_params(step_dir) -> device tree`` does the rank-shard read
        + host→device transfer (built by the service from the player's
        extract rule); ``on_reload(generation, step)`` is a notification
        hook (stats, logs).  ``failure_threshold`` consecutive failed loads
        of the same snapshot quarantine it (when ``quarantine``) and open
        the breaker for ``breaker_reset_s``."""
        self._ckpt_root = ckpt_root
        self._store = store
        self._load_params = load_params
        self._poll_s = float(poll_s)
        self._on_reload = on_reload
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._poll_lock = threading.Lock()
        self._quarantine = bool(quarantine)
        self.breaker = CircuitBreaker(
            failure_threshold=failure_threshold,
            reset_timeout_s=breaker_reset_s,
            name="serve.reload",
        )
        # consecutive-failure tracking is per SNAPSHOT: a new commit landing
        # mid-streak must get a fresh budget, not inherit the poisoned one's
        self._failing_step: Optional[int] = None
        self._failing_count = 0
        self.reloads = 0
        self.quarantined = 0
        self.last_error: Optional[str] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="sheeprl-serve-reload", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def degraded(self) -> bool:
        """Serving old params because new commits cannot be loaded."""
        return self.breaker.state != CircuitBreaker.CLOSED

    def health(self) -> Dict[str, Any]:
        """Breaker/quarantine state for ``/healthz`` and ``/v1/stats``."""
        return {
            "breaker": self.breaker.snapshot(),
            "degraded": self.degraded,
            "reloads": self.reloads,
            "quarantined": self.quarantined,
            "last_error": self.last_error,
        }

    def poll_once(self) -> Optional[int]:
        """One synchronous check (also used by the HTTP ``/v1/reload``
        endpoint and tests): swap if a newer commit exists, return the new
        generation or None.  Serialized: a concurrent poll (watcher thread +
        ``/v1/reload`` handler) could otherwise finish a SLOW load of step N
        after a faster poll already swapped to N+1 and roll the server back
        to stale params — the lock makes every check-load-swap atomic, and
        the entry check rereads ``store.step`` so the loser just no-ops."""
        from sheeprl_tpu.checkpoint.protocol import checkpoint_step, newer_checkpoint

        with self._poll_lock:
            found = newer_checkpoint(self._ckpt_root, self._store.step)
            if found is None:
                return None
            if not self.breaker.allow():
                # open breaker: keep serving old params, don't hammer a
                # snapshot that just failed repeatedly — retry after the
                # cool-down (half-open probe)
                return None
            found_step = checkpoint_step(found)
            try:
                # CRC-verify BEFORE unpickling: a bit flip in raw array data
                # unpickles "successfully" into poisoned params — the
                # manifest check is the only way to catch it
                from sheeprl_tpu.checkpoint.protocol import verify_checkpoint

                problems = verify_checkpoint(found)
                if problems:
                    raise IOError(f"snapshot failed verification: {'; '.join(problems)}")
                new_params = self._load_params(found)
                # the transfer above allocated fresh device buffers; fence it
                # so the swap publishes a fully-materialized tree
                for leaf in jax.tree_util.tree_leaves(new_params):
                    if hasattr(leaf, "block_until_ready"):
                        leaf.block_until_ready()
            except Exception as e:  # a torn read mid-GC, OOM, … — keep serving
                self.last_error = f"{type(e).__name__}: {e}"
                self._record_failure(found, found_step)
                return None
            gen = self._store.swap(new_params, found_step)
            self.reloads += 1
            self.last_error = None
            self._failing_step, self._failing_count = None, 0
            self.breaker.record_success()
            if self._on_reload is not None:
                self._on_reload(gen, self._store.step)
            return gen

    def _record_failure(self, found: Any, found_step: int) -> None:
        """Count consecutive failures of one snapshot; at the threshold,
        quarantine it so discovery moves past the poison."""
        if self._failing_step == found_step:
            self._failing_count += 1
        else:
            self._failing_step, self._failing_count = found_step, 1
        self.breaker.record_failure()
        if self._quarantine and self._failing_count >= self.breaker.failure_threshold:
            from sheeprl_tpu.checkpoint.protocol import quarantine_checkpoint

            target = quarantine_checkpoint(found)
            if target is not None:
                self.quarantined += 1
                self.last_error = (
                    f"{self.last_error} — quarantined {found} after "
                    f"{self._failing_count} failed loads"
                )
            self._failing_step, self._failing_count = None, 0

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # never let the watcher die silently
                self.last_error = f"{type(e).__name__}: {e}"
            self._stop.wait(self._poll_s)
