"""Stdlib HTTP surface over :class:`~sheeprl_tpu.serve.service.PolicyService`.

One ``ThreadingHTTPServer`` per served model: every connection handler
thread just submits into the service's admission queue and blocks on its
request future, so the continuous batcher coalesces across HTTP
connections exactly as it does for in-process callers.  No third-party
web framework — ``http.server`` + JSON is deliberate (the container bakes
no extra deps, and the hot path is the device dispatch, not the parsing).

Endpoints (all JSON):

* ``POST /v1/act``    — ``{"obs": {...}, "greedy"?: bool, "session"?: str}``
  → ``{"action": [...], "shape": [...], "dtype": "...", "generation": n}``
* ``POST /v1/reset``  — ``{"session": str}`` drops a stateful episode carry
* ``POST /v1/reload`` — force one commit-watch poll; reports if it swapped
* ``GET  /v1/session_carry?session=x`` / ``POST /v1/session_carry`` — read /
  install a CRC-stamped latent-carry snapshot (the fleet router's session
  migration primitive; see docs/serving.md "Fleet")
* ``GET  /v1/stats``  — the service's full stats dict (latency percentiles,
  batch/padding counters, reload generation, Compile/* totals)
* ``GET  /healthz``   — liveness + model identity

Observation arrays travel either as nested JSON lists or as packed
``{"__nd__": {"b64": ..., "shape": [...], "dtype": "..."}}`` blobs
(base64 of the raw C-order buffer — the cheap encoding for pixels).
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.serve.batcher import QueueFull, ServiceStopped


def decode_array(value: Any, dtype: Optional[str] = None) -> np.ndarray:
    """JSON value → ndarray: nested lists, or a packed ``__nd__`` blob."""
    if isinstance(value, dict) and "__nd__" in value:
        nd = value["__nd__"]
        buf = base64.b64decode(nd["b64"])
        return np.frombuffer(buf, dtype=np.dtype(nd["dtype"])).reshape(nd["shape"]).copy()
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(np.dtype(dtype), copy=False)
    return arr


def encode_array(arr: np.ndarray, packed: bool = False) -> Any:
    """ndarray → JSON value (packed base64 blob or nested lists)."""
    arr = np.asarray(arr)
    if packed:
        return {
            "__nd__": {
                "b64": base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode("ascii"),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
        }
    return arr.tolist()


class PolicyServer:
    """HTTP wrapper owning a started :class:`PolicyService`.

    ``port=0`` binds an ephemeral port (tests); :attr:`address` is the
    resolved ``(host, port)`` after :meth:`start`.
    """

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0):
        class _ReplicaHTTPServer(ThreadingHTTPServer):
            # a fleet router opens a connection per forwarded request (plus
            # health probes); the stdlib default backlog of 5 RSTs
            # connections under concurrent load
            request_queue_size = 128

        self.service = service
        self._httpd = _ReplicaHTTPServer((host, port), _make_handler(service))
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "PolicyServer":
        self.service.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="sheeprl-serve-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
        self.service.stop()

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def serve_forever(self) -> None:
        """Foreground loop for the CLI entry (Ctrl-C → clean shutdown)."""
        self.service.start()
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self._httpd.server_close()
            self.service.stop()


def _make_handler(service: Any):
    class Handler(BaseHTTPRequestHandler):
        # one handler class per service instance (closure, no globals)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args: Any) -> None:  # quiet by default
            pass

        # -- plumbing ------------------------------------------------------
        def _reply(self, code: int, payload: Dict[str, Any]) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Dict[str, Any]:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        # -- routes --------------------------------------------------------
        def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
            try:
                # chaos-drill injection site: raise → 500 (client retries
                # idempotent requests), hang/latency → a slow or stuck reply
                from sheeprl_tpu.resilience.faults import fault_point

                fault_point("serve.http")
                if self.path == "/healthz":
                    watcher = service.watcher
                    self._reply(
                        200,
                        {
                            "ok": True,
                            # degraded: the reload breaker is open/half-open —
                            # new commits fail to load and the server keeps
                            # serving the OLD params (liveness over freshness)
                            "degraded": watcher.degraded if watcher else False,
                            "reload_breaker": (
                                watcher.breaker.snapshot() if watcher else None
                            ),
                            "algo": service.player.algo,
                            "checkpoint_step": service.store.step,
                            "generation": service.store.generation,
                            # per-request observation contract: key -> [shape, dtype]
                            "obs_spec": {
                                k: [list(shape), dt]
                                for k, (shape, dt) in service.player.obs_spec.items()
                            },
                            "action_shape": list(service.player.action_shape),
                            "stateful": service.player.stateful,
                        },
                    )
                elif self.path == "/v1/stats":
                    self._reply(200, service.stats())
                elif self.path.startswith("/v1/session_carry"):
                    # ?session=<id> → that session's CRC-stamped carry
                    # snapshot (null for unknown sessions / stateless
                    # players) — the fleet router's migration read
                    from urllib.parse import parse_qs, urlparse

                    query = parse_qs(urlparse(self.path).query)
                    session = (query.get("session") or [""])[0]
                    if not session:
                        self._reply(400, {"error": "session_carry requires ?session=<id>"})
                    else:
                        self._reply(
                            200,
                            {"session": session, "snapshot": service.get_session_carry(session)},
                        )
                elif self.path == "/metrics":
                    # the training-side introspection contract on the serve
                    # surface: every telemetry-hub metric (Serve/* included —
                    # the service registers itself on start) in Prometheus
                    # text exposition format
                    from sheeprl_tpu.telemetry import (
                        HUB,
                        PROMETHEUS_CONTENT_TYPE,
                        prometheus_text,
                    )

                    body = prometheus_text(HUB.collect()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except BrokenPipeError:
                pass
            except Exception as e:
                self._safe_error(500, e)

        def do_POST(self) -> None:  # noqa: N802
            try:
                from sheeprl_tpu.resilience.faults import fault_point

                fault_point("serve.http")
                if self.path == "/v1/act":
                    self._act()
                elif self.path == "/v1/reset":
                    body = self._read_json()
                    service.reset_session(str(body.get("session", "")))
                    self._reply(200, {"ok": True})
                elif self.path == "/v1/reload":
                    gen = service.watcher.poll_once() if service.watcher else None
                    self._reply(
                        200,
                        {
                            "reloaded": gen is not None,
                            "generation": service.store.generation,
                            "checkpoint_step": service.store.step,
                        },
                    )
                elif self.path == "/v1/session_carry":
                    # install a migrated carry snapshot (the fleet router's
                    # replay onto a surviving replica); validation failures
                    # are 400s — the router must see them, not a zero carry
                    body = self._read_json()
                    session = str(body.get("session", ""))
                    snapshot = body.get("snapshot")
                    if not session or not isinstance(snapshot, dict):
                        self._reply(
                            400, {"error": "session_carry requires 'session' and 'snapshot'"}
                        )
                        return
                    try:
                        service.restore_session_carry(session, snapshot)
                    except ValueError as e:
                        self._reply(400, {"error": str(e)})
                        return
                    self._reply(200, {"ok": True, "session": session})
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except BrokenPipeError:
                pass
            except Exception as e:
                self._safe_error(500, e)

        def _act(self) -> None:
            body = self._read_json()
            raw = body.get("obs")
            if not isinstance(raw, dict):
                self._reply(400, {"error": "body must carry an 'obs' dict"})
                return
            spec = service.player.obs_spec
            missing = sorted(set(spec) - set(raw))
            if missing:
                self._reply(400, {"error": f"missing obs keys: {missing}"})
                return
            obs = {k: decode_array(raw[k], dtype=spec[k][1]) for k in spec}
            try:
                # generation captured around the wait: the acting params'
                # generation is whatever the dispatch snapshotted, which lies
                # between these two reads — report the post-dispatch one
                action = service.act(
                    obs,
                    greedy=body.get("greedy"),
                    session=body.get("session"),
                    timeout=float(body.get("timeout", 30.0)),
                    block=False,  # full queue → 429 now, not a pinned thread
                )
            except QueueFull as e:
                self._reply(429, {"error": str(e)})
                return
            except ServiceStopped as e:
                self._reply(503, {"error": str(e)})
                return
            except TimeoutError as e:
                self._reply(504, {"error": str(e)})
                return
            action = np.asarray(action)
            payload = {
                "action": encode_array(action, packed=bool(body.get("packed"))),
                "shape": list(action.shape),
                "dtype": str(action.dtype),
                "generation": service.store.generation,
                "checkpoint_step": service.store.step,
            }
            session = body.get("session")
            if body.get("return_carry") and session is not None:
                # fleet carry mirroring: the POST-step carry rides the act
                # response, so the router's mirror is updated atomically
                # with the step it reflects (no probe race window)
                payload["carry"] = service.get_session_carry(str(session))
            self._reply(200, payload)

        def _safe_error(self, code: int, e: Exception) -> None:
            try:
                self._reply(code, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    return Handler
