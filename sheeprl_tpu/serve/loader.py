"""Checkpoint discovery + player rebuild — THE snapshot-reconstruction path.

Both the serving layer and ``sheeprl_tpu.cli:evaluation`` go through here,
so a policy can never be reconstructed two different ways.  Discovery
accepts every checkpoint spelling in the wild:

* a committed ``step_*`` snapshot directory (the commit protocol's unit),
* a ``<run>/version_*/checkpoint`` root (→ newest COMMITTED snapshot),
* a ``version_*`` / run directory (→ its checkpoint root),
* a legacy flat ``ckpt_*.ckpt`` file.

The run's ``config.yaml`` is found by walking up from the checkpoint (it
lives next to the ``checkpoint`` directory), merged under any CLI
overrides, and the player network is rebuilt by the per-algorithm builder
registered in :mod:`sheeprl_tpu.serve.players`.
"""

from __future__ import annotations

import pathlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sheeprl_tpu.config.compose import ConfigError
from sheeprl_tpu.utils.structured import dotdict


def resolve_checkpoint(path: Any, verify: bool = True) -> pathlib.Path:
    """Resolve any checkpoint spelling to a loadable target: a committed
    ``step_*`` directory or a legacy ``.ckpt`` file.

    With ``verify`` (the default), a resolved snapshot's shards are CRC-
    checked against its manifest BEFORE it is trusted: a damaged snapshot
    found under a root is quarantined (``step_*`` → ``step_*.corrupt``) and
    the next newest committed one is used instead — serving/evaluation skip
    bit rot instead of crashing on it.  An EXPLICITLY named ``step_*``
    directory that fails verification raises (it is never renamed behind
    the caller's back)."""
    from sheeprl_tpu.checkpoint import is_committed
    from sheeprl_tpu.checkpoint.protocol import (
        checkpoint_step,
        verify_checkpoint,
        verify_or_quarantine,
    )

    p = pathlib.Path(path)
    if p.is_file():  # legacy flat file
        return p
    if not p.exists():
        raise ConfigError(f"checkpoint path does not exist: {p}")
    if checkpoint_step(p) >= 0:  # an explicit step_* directory
        if not is_committed(p):
            raise ConfigError(
                f"{p} is an uncommitted (torn) snapshot — it has no COMMIT "
                "marker and cannot be served or evaluated"
            )
        if verify:
            problems = verify_checkpoint(p)
            if problems:
                raise ConfigError(
                    f"{p} is a damaged snapshot ({'; '.join(problems)}) and "
                    "cannot be served or evaluated"
                )
        return p
    # a checkpoint root, version dir, or run dir: find the newest committed
    # snapshot underneath (searching <p>/checkpoint first, then <p> itself,
    # then any version_*/checkpoint)
    candidates = [p / "checkpoint", p]
    candidates += sorted(
        p.glob("version_*/checkpoint"),
        key=lambda d: int(d.parent.name.rsplit("_", 1)[-1]),
        reverse=True,
    )
    from sheeprl_tpu.checkpoint import list_checkpoints

    damaged: set = set()
    for root in candidates:
        if not root.is_dir():
            continue
        # newest first; skip known-damaged entries rather than breaking out,
        # so a quarantine rename failing (read-only store) still falls back
        # to the older intact commits under the same root
        for candidate in reversed(list_checkpoints(root)):
            if candidate in damaged:
                continue
            if not verify or not verify_or_quarantine(candidate):
                return candidate
            damaged.add(candidate)
            import warnings

            warnings.warn(
                f"skipping damaged snapshot {candidate} (quarantined); trying "
                "the next committed one",
                RuntimeWarning,
            )
    # legacy flat layout fallback
    for root in candidates:
        if root.is_dir():
            ckpts = sorted(root.glob("ckpt_*.ckpt"), key=lambda f: f.stat().st_mtime)
            if ckpts:
                return ckpts[-1]
    raise ConfigError(f"no committed checkpoint found under {p}")


def checkpoint_root(ckpt: Any) -> pathlib.Path:
    """The directory :func:`~sheeprl_tpu.checkpoint.latest_checkpoint` polls
    for newer commits — the parent ``checkpoint`` dir of a resolved target."""
    return pathlib.Path(ckpt).parent


def load_run_config(ckpt: Any, overrides: Sequence[str] = ()) -> dotdict:
    """The run's saved ``config.yaml`` (found next to the checkpoint dir),
    with ``overrides`` applied on top."""
    import yaml

    from sheeprl_tpu.config.compose import apply_cli_overrides

    ckpt = pathlib.Path(ckpt)
    # <version>/checkpoint/step_*  or  <version>/checkpoint/ckpt_*.ckpt
    for parent in ckpt.parents:
        cfg_path = parent / "config.yaml"
        if cfg_path.is_file():
            with open(cfg_path) as f:
                cfg = dotdict(yaml.safe_load(f))
            if overrides:
                apply_cli_overrides(cfg, list(overrides))
            return cfg
    raise ConfigError(f"cannot find the run config next to the checkpoint: {ckpt}")


def serve_defaults() -> Dict[str, Any]:
    """The ``serve`` config group's defaults — run configs saved before the
    serving layer existed have no ``serve`` section, so callers merge this
    underneath."""
    from sheeprl_tpu.config.compose import _find_config_file, _load_yaml, _search_dirs

    path = _find_config_file("serve/default.yaml", _search_dirs())
    return _load_yaml(path) if path is not None else {}


def ensure_serve_config(cfg: dotdict) -> dotdict:
    """Merge the serve defaults UNDER whatever the run config/overrides set."""
    from sheeprl_tpu.utils.structured import deep_merge

    merged = deep_merge({"serve": serve_defaults()}, cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    return dotdict(merged)


def probe_spaces(cfg: dotdict) -> Tuple[Any, Any]:
    """Observation/action spaces from ONE probe env instance (exactly how the
    evaluation entrypoints derive them)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0)()
    obs_space, action_space = env.observation_space, env.action_space
    env.close()
    return obs_space, action_space


def build_player(fabric: Any, cfg: dotdict, state: Dict[str, Any]) -> Any:
    """Rebuild the serving player for ``cfg.algo.name`` from a loaded
    checkpoint state."""
    from sheeprl_tpu.serve.players import PLAYER_BUILDERS

    algo = cfg.algo.name
    builder = PLAYER_BUILDERS.get(algo)
    if builder is None:
        raise ConfigError(
            f"no serving player registered for algorithm '{algo}' "
            f"(available: {', '.join(sorted(PLAYER_BUILDERS))})"
        )
    obs_space, action_space = probe_spaces(cfg)
    return builder(fabric, cfg, state, obs_space, action_space)


def evaluate_player(
    fabric: Any,
    cfg: dotdict,
    player: Any,
    log_dir: Optional[str] = None,
    logger: Any = None,
    greedy: bool = True,
) -> float:
    """One evaluation episode through the SERVING player — the same
    prepare → AOT step → postprocess path ``PolicyService`` dispatches, so
    ``sheeprl_tpu.cli:evaluation`` and the server can never disagree on how
    a snapshot acts.  Returns the cumulative reward (logged as
    ``Test/cumulative_reward`` when a logger is passed)."""
    import numpy as np

    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    obs, _ = env.reset(seed=cfg.seed)
    carry = player.zero_carry_row() if player.stateful else ()
    greedy_mask = np.asarray([greedy], bool)
    seed = int(cfg.seed)
    done, cum_reward = False, 0.0
    while not done:
        batched = {k: np.asarray(obs[k])[None] for k in player.obs_spec}
        carry, actions = player.step_batch(
            player.params, carry, player.prepare(batched), seed, greedy_mask
        )
        seed += 1
        obs, reward, terminated, truncated, _ = env.step(player.postprocess(actions[:1])[0])
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward


def load_policy(
    checkpoint_path: Any,
    overrides: Sequence[str] = (),
    fabric: Optional[Any] = None,
    cfg: Optional[dotdict] = None,
) -> Tuple[Any, dotdict, Dict[str, Any], Any]:
    """One-call snapshot → policy reconstruction.

    Returns ``(fabric, cfg, state, player)``.  Serving (like evaluation) is
    single-device, single-env: the loaded run config is forced to
    ``fabric.devices=1`` / ``env.num_envs=1`` after the overrides so an
    ``env=<group>`` swap cannot resurrect a group's env-count default.
    ``cfg`` lets a caller that already ran :func:`load_run_config` (the
    evaluation CLI peeks at ``algo.name`` first) hand its copy over instead
    of parsing the run YAML twice; it is mutated in place as above.
    """
    from sheeprl_tpu.checkpoint.protocol import checkpoint_step
    from sheeprl_tpu.parallel.fabric import build_fabric

    ckpt = resolve_checkpoint(checkpoint_path)
    if cfg is None:
        cfg = load_run_config(ckpt, overrides)
    cfg.fabric.devices = 1
    cfg.env.num_envs = 1
    cfg.env.capture_video = cfg.env.get("capture_video", False)
    cfg = ensure_serve_config(cfg)

    import sheeprl_tpu

    sheeprl_tpu.register_all_algorithms()
    if fabric is None:
        fabric = build_fabric(cfg)
    # a retention pass (gc_checkpoints) on the training side can delete the
    # snapshot between discovery and read: re-resolve a NEWER committed one
    # and retry instead of crashing — by the commit protocol, a newer commit
    # always exists before GC removes an older snapshot
    try:
        state = fabric.load(ckpt)
    except FileNotFoundError:
        from sheeprl_tpu.resilience.retry import retry

        def reresolve_and_load():
            nonlocal ckpt
            ckpt = resolve_checkpoint(checkpoint_path)
            return fabric.load(ckpt)

        state = retry(
            reresolve_and_load,
            attempts=3,
            base_s=0.2,
            retry_on=(FileNotFoundError,),
            site="serve.load",
        )
    player = build_player(fabric, cfg, state)
    player.checkpoint_step = checkpoint_step(ckpt)
    return fabric, cfg, state, player
