"""Stdlib client for the ``sheeprl_tpu.serve`` HTTP surface.

``PolicyClient`` is a thin, dependency-free wrapper over
``urllib.request`` — the same JSON protocol ``serve/server.py`` speaks,
including the packed base64 array encoding for pixel observations.  Use
``session=`` for stateful policies (dreamer_v3): the server keeps one
latent carry per session id, reset at episode boundaries via
:meth:`PolicyClient.reset`.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.serve.server import decode_array, encode_array


class ServerError(RuntimeError):
    """Non-2xx response from the policy server."""

    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class PolicyClient:
    def __init__(self, base_url: str, timeout: float = 30.0, packed: bool = False):
        """``packed=True`` ships/returns arrays as base64 blobs instead of
        nested JSON lists — much cheaper for image observations."""
        self.base_url = base_url.rstrip("/")
        self.timeout = float(timeout)
        self.packed = bool(packed)

    # -- transport ----------------------------------------------------------
    def _call(self, method: str, path: str, body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read() or b"{}").get("error", str(e))
            except Exception:
                message = str(e)
            raise ServerError(e.code, message) from None

    # -- API ----------------------------------------------------------------
    def act(
        self,
        obs: Dict[str, np.ndarray],
        greedy: Optional[bool] = None,
        session: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        body: Dict[str, Any] = {
            "obs": {k: encode_array(np.asarray(v), packed=self.packed) for k, v in obs.items()},
            "packed": self.packed,
        }
        if greedy is not None:
            body["greedy"] = bool(greedy)
        if session is not None:
            body["session"] = session
        if timeout is not None:
            body["timeout"] = float(timeout)
        out = self._call("POST", "/v1/act", body)
        action = decode_array(out["action"], dtype=out.get("dtype"))
        self.last_generation = out.get("generation")
        self.last_checkpoint_step = out.get("checkpoint_step")
        return np.asarray(action).reshape(out.get("shape", np.asarray(action).shape))

    def reset(self, session: str) -> None:
        self._call("POST", "/v1/reset", {"session": session})

    def reload(self) -> Dict[str, Any]:
        """Force one commit-watch poll on the server."""
        return self._call("POST", "/v1/reload", {})

    def stats(self) -> Dict[str, Any]:
        return self._call("GET", "/v1/stats")

    def health(self) -> Dict[str, Any]:
        return self._call("GET", "/healthz")
