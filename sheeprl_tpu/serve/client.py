"""Stdlib client for the ``sheeprl_tpu.serve`` HTTP surface.

``PolicyClient`` is a thin, dependency-free wrapper over
``urllib.request`` — the same JSON protocol ``serve/server.py`` speaks,
including the packed base64 array encoding for pixel observations.  Use
``session=`` for stateful policies (dreamer_v3): the server keeps one
latent carry per session id, reset at episode boundaries via
:meth:`PolicyClient.reset`.

Error surfacing + liveness:

* every non-2xx answer raises a typed :class:`ServeRequestError` carrying
  the HTTP status and a (truncated) copy of the raw body — non-JSON error
  pages (a proxy's HTML 502, a half-written response) are no longer
  swallowed into a bare re-raise;
* connection-level errors (refused, reset, timeout) and 5xx answers to
  **idempotent** requests are retried with jittered exponential backoff
  (``retries``/``retry_base_s``), so a server mid-restart or an injected
  ``serve.http`` fault costs latency, not a dropped request.  ``act`` is
  idempotent exactly when it carries no ``session`` (a stateful act
  advances the server-side latent carry, so a response lost on the wire
  must not be silently replayed); 429 (load shed) and other 4xx are never
  retried — they are the server telling the client to back off or fix the
  request.  The one exception: **503 is retried even for session-bearing
  acts** — a 503 (single server stopping, or the fleet router's
  ``replica_unavailable``) certifies the request was never dispatched, so
  no carry advanced, and against a fleet router the retry lands on a
  re-routed healthy replica (docs/serving.md "Fleet").
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.serve.server import decode_array, encode_array

#: bytes of a non-JSON error body kept on the exception
_BODY_TRUNCATE = 512


class ServeRequestError(RuntimeError):
    """Non-2xx response from the policy server.

    ``status`` is the HTTP code; ``body`` is the error body — the server's
    JSON ``error`` field when parseable, otherwise the raw payload decoded
    and truncated to ~512 chars (so a proxy's HTML error page stays
    diagnosable instead of vanishing into a bare re-raise).
    """

    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = int(status)
        self.body = body


#: Backwards-compatible alias (the pre-resilience exception name).
ServerError = ServeRequestError


class PolicyClient:
    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        packed: bool = False,
        retries: int = 3,
        retry_base_s: float = 0.2,
    ):
        """``packed=True`` ships/returns arrays as base64 blobs instead of
        nested JSON lists — much cheaper for image observations.
        ``retries`` bounds the transparent retry of connection errors and
        of 5xx answers to idempotent requests (1 = never retry)."""
        self.base_url = base_url.rstrip("/")
        self.timeout = float(timeout)
        self.packed = bool(packed)
        self.retries = max(1, int(retries))
        self.retry_base_s = float(retry_base_s)

    # -- transport ----------------------------------------------------------
    def _call_once(self, method: str, path: str, body: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raw = b""
            try:
                raw = e.read() or b""
            except Exception:
                pass
            try:
                message = json.loads(raw)["error"]
            except Exception:
                # non-JSON body: surface it (truncated), not a bare re-raise
                message = raw.decode("utf-8", "replace")[:_BODY_TRUNCATE] or str(e)
            raise ServeRequestError(e.code, message) from None

    def _call(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        idempotent: bool = True,
    ) -> Dict[str, Any]:
        from sheeprl_tpu.resilience.retry import retry

        def transient(e: BaseException) -> bool:
            if isinstance(e, ServeRequestError):
                # 503 is retried even for session-bearing acts: both
                # spellings of it (a single server mid-stop, the fleet
                # router's replica_unavailable) mean the request was NEVER
                # dispatched — no carry advanced, so a replay cannot
                # double-step the episode, and the fleet router re-routes
                # the session to a healthy replica on the retry
                if e.status == 503:
                    return True
                # other 5xx only when replaying the request is safe
                return idempotent and e.status >= 500
            # URLError (refused/reset/DNS), timeouts, dropped connections:
            # for non-idempotent requests only connection-REFUSED-class
            # errors are safely retriable (the request never reached the
            # server); a mid-flight drop might have been processed
            if isinstance(e, urllib.error.URLError):
                return idempotent or isinstance(e.reason, ConnectionRefusedError)
            return idempotent and isinstance(e, (ConnectionError, TimeoutError, OSError))

        return retry(
            lambda: self._call_once(method, path, body),
            attempts=self.retries,
            base_s=self.retry_base_s,
            max_s=5.0,
            retry_on=(ServeRequestError, urllib.error.URLError, ConnectionError, TimeoutError, OSError),
            should_retry=transient,
            site="serve.client",
        )

    # -- API ----------------------------------------------------------------
    def act(
        self,
        obs: Dict[str, np.ndarray],
        greedy: Optional[bool] = None,
        session: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        body: Dict[str, Any] = {
            "obs": {k: encode_array(np.asarray(v), packed=self.packed) for k, v in obs.items()},
            "packed": self.packed,
        }
        if greedy is not None:
            body["greedy"] = bool(greedy)
        if session is not None:
            body["session"] = session
        if timeout is not None:
            body["timeout"] = float(timeout)
        # a stateful act advances the server-side carry: replaying it after
        # a lost response would double-step the episode — not idempotent
        out = self._call("POST", "/v1/act", body, idempotent=session is None)
        action = decode_array(out["action"], dtype=out.get("dtype"))
        self.last_generation = out.get("generation")
        self.last_checkpoint_step = out.get("checkpoint_step")
        return np.asarray(action).reshape(out.get("shape", np.asarray(action).shape))

    def reset(self, session: str) -> None:
        # dropping a carry twice is harmless — idempotent
        self._call("POST", "/v1/reset", {"session": session})

    def reload(self) -> Dict[str, Any]:
        """Force one commit-watch poll on the server."""
        return self._call("POST", "/v1/reload", {})

    def session_carry(self, session: str) -> Optional[Dict[str, Any]]:
        """Read a session's CRC-stamped carry snapshot (None when the
        server has no carry for it / the player is stateless)."""
        from urllib.parse import quote

        out = self._call("GET", f"/v1/session_carry?session={quote(session, safe='')}")
        return out.get("snapshot")

    def restore_session_carry(self, session: str, snapshot: Dict[str, Any]) -> Dict[str, Any]:
        """Install a carry snapshot under ``session`` (migration replay).
        NOT idempotent-marked on purpose: a restore is only replayed on
        connection-refused, matching the act contract."""
        return self._call(
            "POST",
            "/v1/session_carry",
            {"session": session, "snapshot": snapshot},
            idempotent=False,
        )

    def stats(self) -> Dict[str, Any]:
        return self._call("GET", "/v1/stats")

    def health(self) -> Dict[str, Any]:
        return self._call("GET", "/healthz")
