"""``python -m sheeprl_tpu.serve checkpoint_path=<ckpt> [overrides...]``"""

from sheeprl_tpu.cli import serve

if __name__ == "__main__":
    serve()
