"""sheeprl_tpu.algos."""
