"""Shared Plan2Explore state plumbing.

Every P2E variant stores TWO policies in its exploration checkpoint: the
exploration actor under ``"actor"`` (the one the player acts with during
exploration) and the task policy under ``"actor_task"``.  Evaluation and
finetuning pick between them via ``algo.player.actor_type``
(reference: sheeprl/algos/p2e_dv*/p2e_dv*_finetuning.py switch to the task
actor; evaluation honors the configured type).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence


def actor_type_from_cfg(cfg: Any) -> str:
    return cfg.algo.get("player", {}).get("actor_type", "task")


def choose_actor(agent: Dict[str, Any], cfg: Any) -> Dict[str, Any]:
    """Swap the task actor into the ``"actor"`` slot when configured (and
    available — pre-dual-policy checkpoints only carry ``"actor"``)."""
    if "actor_task" in agent and actor_type_from_cfg(cfg) == "task":
        return {**agent, "actor": agent["actor_task"]}
    return agent


def project_exploration_state(
    state: Dict[str, Any],
    actor_type: str,
    keep_keys: Sequence[str],
    defaults: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Project an exploration checkpoint onto a base-Dreamer state layout:
    keep ``keep_keys`` (world model, task critic/target, ...), select the
    actor by ``actor_type``, and fill ``defaults`` for keys the checkpoint
    may predate."""
    agent = dict(state.get("agent", {}))
    chosen_actor = agent.get("actor_task") if actor_type == "task" else agent.get("actor")
    projected = {k: agent[k] for k in keep_keys if k in agent}
    for k, v in (defaults or {}).items():
        projected.setdefault(k, agent.get(k, v))
    projected["actor"] = chosen_actor if chosen_actor is not None else agent["actor"]
    out = {"agent": projected}
    if "rb" in state:
        out["rb"] = state["rb"]
    return out


def ensemble_disagreement(preds, multiplier: float):
    """Plan2Explore intrinsic reward: UNBIASED variance of the ensemble's
    next-state predictions, averaged over the feature dim
    (reference: sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py:283 —
    ``next_state_embedding.var(0).mean(-1) * multiplier``; torch's ``var``
    uses the N-1 divisor, hence ddof=1).

    ``preds``: (n_ensembles, ..., feature_dim).
    """
    return preds.var(0, ddof=1).mean(-1) * multiplier
