"""SAC support utilities (reference: sheeprl/algos/sac/utils.py:1-103)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(obs: Dict[str, np.ndarray], mlp_keys: Sequence[str]) -> jax.Array:
    """Concatenate the vector observation keys into one float32 matrix
    (SAC is vector-obs; pixels are SAC-AE's job)."""
    import jax.numpy as jnp

    parts = [np.asarray(obs[k], np.float32).reshape(np.asarray(obs[k]).shape[0], -1) for k in mlp_keys]
    return jnp.asarray(np.concatenate(parts, axis=-1))


def test(actor: Any, params: Any, cfg: Any, log_dir: str, logger: Any = None, greedy: bool = True) -> float:
    """Greedy evaluation episode (reference: sheeprl/algos/sac/utils.py:test)."""
    import jax.numpy as jnp

    from sheeprl_tpu.algos.sac.agent import sample_action
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)

    @jax.jit
    def act(p, o, k):
        a, _ = sample_action(actor, p, o, k, greedy=greedy)
        return a

    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    done, cum_reward = False, 0.0
    low = np.asarray(env.action_space.low, np.float32)
    high = np.asarray(env.action_space.high, np.float32)
    while not done:
        batched = {k: np.asarray(v)[None] for k, v in obs.items()}
        o = prepare_obs(batched, mlp_keys)
        key, sk = jax.random.split(key)
        action = np.asarray(act(params, o, sk))[0]
        # actor outputs [-1, 1]; rescale to the env's bounds
        scaled = low + (action + 1.0) * 0.5 * (high - low)
        obs, reward, terminated, truncated, _ = env.step(scaled)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward
