"""SAC, coupled topology (off-policy path of the build plan, SURVEY.md §7.4).

Capability parity with the reference train script
(reference: sheeprl/algos/sac/sac.py:81-427): uniform replay, twin-Q with
EMA targets, squashed-Gaussian actor, automatic temperature tuning with the
α-gradient synchronized across the world (reference: sac.py:68-73 — here the
mean over the globally-sharded batch does it), ``Ratio``-governed gradient
steps per env step, learning_starts prefill with random actions.

TPU-native structure:
* host player selects actions (CPU copy of actor params, refreshed after
  each train dispatch);
* each iteration's gradient steps run as ONE jitted dispatch — the replay
  batch block for ALL steps of the window is sampled host-side in one call
  (n_samples × batch, the reference's own bulk pattern,
  reference: dreamer_v3.py:664-671) and scanned over on device;
* actions live in the actor's tanh space [-1, 1] inside the framework and
  are rescaled to env bounds only at the env boundary.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.agent import build_agent, ema_update, sample_action
from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss, critic_loss
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import (
    DeviceReplay,
    HostSpill,
    estimate_step_bytes,
    fit_hbm_window,
    fused_uniform_train,
    resolve_device_replay,
    steady_guard,
    update_chunks,
)
from sheeprl_tpu.checkpoint.rollback import rollback_state
from sheeprl_tpu.parallel.compile import compile_once
from sheeprl_tpu.parallel.fabric import PlayerSync
from sheeprl_tpu.resilience.health import DivergenceError, HealthSentinel
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs, TrainWindow, window_scan


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.sac.agent import build_agent as sac_build_agent

    def plain_apply(critic, cp, o, a, k):
        return critic.apply(cp, o, a)

    sac_loop(fabric, cfg, sac_build_agent, plain_apply)


def make_sac_train_fns(actor, critic, critic_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim):
    """The jitted SAC programs (act + scanned multi-update train phase),
    shared by the coupled loop, DroQ, and the dedicated cross-process
    decoupled topology (reference: the train() shared between
    sheeprl/algos/sac/sac.py:30-79 and sac_decoupled.py's trainer)."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    target_entropy = -float(act_dim)
    target_freq = int(cfg.algo.critic.target_network_frequency)

    def act_fn(p, obs, k, greedy=False):
        # key advances INSIDE the jitted step (one host dispatch per env
        # step instead of three; callers thread the returned key)
        k_sample, k_next = jax.random.split(k)
        a, _ = sample_action(actor, p, obs, k_sample, greedy=greedy)
        return a, k_next

    # compile-once routing (parallel/compile.py): AOT-compiled per abstract
    # signature, counted by the recompile detector
    act_fn = compile_once(
        act_fn,
        name=f"{cfg.algo.name}.act_fn",
        static_argnames=("greedy",),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    def one_update(carry, batch_and_key):
        p, o_state, step_idx = carry
        batch, k = batch_and_key
        k_next, k_pi, k_d1, k_d2, k_d3 = jax.random.split(k, 5)
        alpha = jnp.exp(p["log_alpha"])

        # -- critic
        next_a, next_lp = sample_action(actor, p["actor"], batch["next_obs"], k_next)
        target_qs = critic_apply(critic, p["target_critic"], batch["next_obs"], next_a, k_d1)
        target_v = jnp.min(target_qs, axis=0) - alpha * next_lp
        # bootstrap THROUGH time-limit truncation: only true termination cuts
        # the return (reference: sac.py:46 uses data["terminated"])
        y = batch["rewards"] + gamma * (1.0 - batch["terminated"]) * target_v

        def c_loss(cp):
            qs = critic_apply(critic, cp, batch["obs"], batch["actions"], k_d2)
            return critic_loss(qs, jax.lax.stop_gradient(y))

        vl, c_grads = jax.value_and_grad(c_loss)(p["critic"])
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state["critic"], p["critic"])
        p = {**p, "critic": optax.apply_updates(p["critic"], c_updates)}

        # -- actor
        def a_loss(ap):
            a, lp = sample_action(actor, ap, batch["obs"], k_pi)
            qs = critic_apply(critic, p["critic"], batch["obs"], a, k_d3)
            return actor_loss(alpha, lp, jnp.min(qs, axis=0)), lp

        (pl, lp), a_grads = jax.value_and_grad(a_loss, has_aux=True)(p["actor"])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state["actor"], p["actor"])
        p = {**p, "actor": optax.apply_updates(p["actor"], a_updates)}

        # -- temperature
        def t_loss(la):
            return alpha_loss(la, lp, target_entropy)

        al, t_grads = jax.value_and_grad(t_loss)(p["log_alpha"])
        t_updates, new_t_opt = alpha_opt.update(t_grads, o_state["alpha"], p["log_alpha"])
        p = {**p, "log_alpha": p["log_alpha"] + t_updates}

        # -- EMA target (every target_network_frequency updates,
        #    reference: sac.py target update cadence)
        do_ema = (step_idx % target_freq) == 0
        new_target = ema_update(p["target_critic"], p["critic"], tau)
        p = {
            **p,
            "target_critic": jax.tree.map(
                lambda n, o: jnp.where(do_ema, n, o), new_target, p["target_critic"]
            ),
        }
        o_state = {"actor": new_a_opt, "critic": new_c_opt, "alpha": new_t_opt}
        return (p, o_state, step_idx + 1), (vl, pl, al)

    def train_phase(p, o_state, batches, k, step0):
        """``batches``: dict of (U, batch, ...) stacked update blocks."""
        U = batches["rewards"].shape[0]
        keys = jax.random.split(k, U)
        # conv-free matmul body: scan carries no XLA-CPU penalty here, and
        # SAC windows can be long — keep the compact lowering
        (p, o_state, _), losses = window_scan(
            one_update, (p, o_state, step0), (batches, keys), unroll=False
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), losses)

    train_phase = compile_once(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )
    return act_fn, train_phase


def sac_loop(fabric: Any, cfg: Any, build_agent_fn: Any, critic_apply: Any) -> None:
    """The SAC training engine, shared with DroQ (which injects a
    dropout-active critic apply) — mirroring how the reference derives DroQ
    from SAC (reference: sheeprl/algos/droq/droq.py)."""
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = vectorize(
        cfg,
        [
            make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
            for i in range(num_envs)
        ],
    )
    act_space = envs.single_action_space
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only, like the reference")
    obs_space = envs.single_observation_space
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    for k in mlp_keys:
        if k not in obs_space.spaces:
            raise ValueError(f"mlp key '{k}' not in observation space {list(obs_space.spaces)}")
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))
    act_low = np.asarray(act_space.low, np.float32)
    act_high = np.asarray(act_space.high, np.float32)

    def to_env_actions(a: np.ndarray) -> np.ndarray:
        return act_low + (a + 1.0) * 0.5 * (act_high - act_low)

    # ---------------- agent -------------------------------------------------
    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    actor, critic, params = build_agent_fn(fabric, act_dim, cfg, obs_dim, state.get("agent"))

    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)
    opt_state = fabric.replicate(
        state.get("opt_state")
        or {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    aggregator = MetricAggregator(
        cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {}
    )
    timer.configure(cfg.metric)

    psync = PlayerSync(fabric, cfg, extract=lambda p: p["actor"])
    host = psync.device  # single resolution of algo.player.device
    act_fn, train_phase = make_sac_train_fns(
        actor, critic, critic_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )
    # training-health sentinels (resilience/health.py): the guarded program
    # wraps the compiled phase (it inlines under the trace, like the fused
    # replay programs) and threads the tiny device HealthState first —
    # health.enabled=false compiles the guard OUT and every call site below
    # keeps the exact unguarded program
    sentinel = HealthSentinel.from_config(cfg, fabric)
    if sentinel is not None:
        sentinel.register()
        train_phase = compile_once(
            sentinel.wrap(train_phase),
            name=f"{cfg.algo.name}.train_phase_guarded",
            donate_argnums=(0, 1, 2),
            max_recompiles=cfg.algo.get("max_recompiles"),
        )
    player_params = psync.init(params)

    # ---------------- counters ----------------------------------------------
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    learning_starts = int(cfg.algo.learning_starts) // policy_steps_per_iter if not cfg.dry_run else 0
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    if state:
        learning_starts += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])
    window = TrainWindow(
        cfg.algo.get("train_window_iters", 1),
        pending=int(state.get("pending_gradient_steps", 0)) if state else 0,
    )
    if state and "psync" in state:
        psync.load_state_dict(state["psync"])

    # ---------------- replay: device-resident HBM ring or host numpy --------
    capacity = int(cfg.buffer.size) // num_envs
    memmap_dir = os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None
    use_device_replay = resolve_device_replay(cfg, fabric.accelerator)
    if use_device_replay:
        # rows: obs + next_obs (copies_per_key=2) + action/reward/flag tail
        step_bytes = estimate_step_bytes(
            obs_space, mlp_keys, extra_bytes=4 * (act_dim + 2), copies_per_key=2
        )
        hbm_window, spill_needed = fit_hbm_window(
            capacity, num_envs, step_bytes, cfg.buffer.get("hbm_window")
        )
        spill = (
            HostSpill(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
            if spill_needed
            else None
        )
        rb: Any = DeviceReplay(
            hbm_window, num_envs, mesh=fabric.mesh, data_axis=fabric.data_axis, spill=spill
        )
    else:
        rb = ReplayBuffer(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    batch_size = int(cfg.algo.per_rank_batch_size) * fabric.local_world_size

    # on-device sampling folded INTO the compiled update (zero H2D in steady
    # state — data/device_replay.py): the fused program draws indices,
    # gathers, and runs the scanned multi-update phase in one dispatch
    train_phase_dev = None
    if use_device_replay:
        def _prep_batch(b):
            return {
                "obs": b["obs"],
                "next_obs": b["next_obs"],
                "actions": b["actions"],
                "rewards": b["rewards"][..., 0],
                "terminated": b["terminated"][..., 0],
            }

        train_phase_dev = fused_uniform_train(
            fabric,
            train_phase,
            rb,
            batch_size,
            _prep_batch,
            name=f"{cfg.algo.name}.train_phase_device",
            max_recompiles=cfg.algo.get("max_recompiles"),
            health=sentinel is not None,
        )
    guard_on = bool(cfg.buffer.get("transfer_guard", False)) and use_device_replay

    # ---------------- main loop ---------------------------------------------
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    obs_vec = np.asarray(prepare_obs(obs, mlp_keys))
    last_losses = None
    counter_dev = None  # device-resident grad-step counter (zero-copy path)
    h_dev = None  # device-resident sentinel state (resilience/health.py)
    train_windows = 0  # completed dispatched windows (guards arm past warmup)
    # per-rank player key stream, advanced inside act_fn; the main `key`
    # stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    from sheeprl_tpu.utils.profiler import ProfilerGate

    profiler = ProfilerGate(cfg, log_dir)
    for update in range(start_iter, total_iters + 1):
        profiler.step(update)
        policy_step += num_envs * fabric.num_processes
        with timer("Time/env_interaction_time"):
            if update <= learning_starts and not state:
                env_actions = np.stack([act_space.sample() for _ in range(num_envs)])
                span = act_high - act_low
                actions = np.clip(2.0 * (env_actions - act_low) / np.where(span == 0, 1, span) - 1.0, -1, 1)
            else:
                with jax.default_device(host):
                    a, player_key = act_fn(player_params, jnp.asarray(obs_vec), player_key)
                    actions = np.asarray(a)
                env_actions = to_env_actions(actions)
            next_obs, rewards, terminated, truncated, info = envs.step(env_actions)
            dones = np.logical_or(terminated, truncated).astype(np.float32)
            rewards = np.asarray(rewards, np.float32)

            next_vec = np.asarray(prepare_obs(next_obs, mlp_keys))
            # real next obs for done envs (autoreset replaced them)
            store_next = next_vec
            done_idx = np.nonzero(dones)[0]
            if done_idx.size:
                final = final_obs_rows(info, done_idx, mlp_keys)
                if final is not None:
                    store_next = next_vec.copy()
                    store_next[done_idx] = np.concatenate(
                        [np.asarray(final[k], np.float32).reshape(done_idx.size, -1) for k in mlp_keys],
                        axis=-1,
                    )

            rb.add(
                {
                    "obs": obs_vec[None],
                    "next_obs": store_next[None],
                    "actions": actions[None].astype(np.float32),
                    "rewards": rewards[None, :, None],
                    "terminated": terminated.astype(np.float32)[None, :, None],
                }
            )
            obs_vec = next_vec
            for ep_ret, ep_len in episode_stats(info):
                aggregator.update("Rewards/rew_avg", ep_ret)
                aggregator.update("Game/ep_len_avg", ep_len)

        # ---------------- training ------------------------------------------
        # train_window_iters K > 1 accrues the Ratio-owed gradient steps over
        # K env iterations and runs them as ONE scanned dispatch: identical
        # update math and count, the per-dispatch fixed cost (host sample,
        # transfer, launch — dominated by tunnel latency on a remote TPU)
        # amortized K-fold.  Data staleness within a window is at most K-1
        # env iterations — the same staleness class as the reference's
        # decoupled trainer (reference: sheeprl/algos/sac/sac_decoupled.py).
        # K = 1 (default) is the reference-coupled cadence, bit-for-bit.
        if update >= learning_starts:
            due = window.push(
                ratio(policy_step / fabric.world_size), update, learning_starts, total_iters
            )
            if due > 0 and train_phase_dev is not None:
                with timer("Time/train_time"):
                    # zero-copy steady state: the batch never exists on the
                    # host — sampling + gather are compiled into the update
                    # dispatch, the step counter rides through the program as
                    # device data, and (optionally) the transfer guard proves
                    # no implicit H2D happens past the first (warmup) window
                    if counter_dev is None:
                        # replicated on the mesh, matching the program's output
                        # placement — a single-device stage would cost one
                        # extra (first-window) executable on multi-device
                        counter_dev = fabric.replicate(np.int32(grad_step_counter))
                    if sentinel is not None and h_dev is None:
                        h_dev = sentinel.init_state()
                    player_params = psync.before_dispatch(player_params)
                    with steady_guard(guard_on and train_windows > 0):
                        for u in update_chunks(
                            due, bytes_per_update=rb.sampled_bytes_per_update(batch_size)
                        ):
                            key, tk = jax.random.split(key)
                            if sentinel is not None:
                                params, opt_state, h_dev, counter_dev, last_losses = (
                                    train_phase_dev(
                                        params, opt_state, h_dev, rb.buffers, rb.cursor,
                                        tk, counter_dev, n_samples=u,
                                    )
                                )
                            else:
                                params, opt_state, counter_dev, last_losses = train_phase_dev(
                                    params, opt_state, rb.buffers, rb.cursor, tk,
                                    counter_dev, n_samples=u,
                                )
                            grad_step_counter += u
                    train_windows += 1
                    player_params = psync.after_dispatch(params, player_params)
            elif due > 0:
                with timer("Time/train_time"):
                    sample = rb.sample(
                        batch_size, n_samples=due
                    )  # (U, batch, *) block in one host call
                    batches = {
                        "obs": jnp.asarray(sample["obs"]),
                        "next_obs": jnp.asarray(sample["next_obs"]),
                        "actions": jnp.asarray(sample["actions"]),
                        "rewards": jnp.asarray(sample["rewards"][..., 0]),
                        "terminated": jnp.asarray(sample["terminated"][..., 0]),
                    }
                    batches = fabric.shard_batch(batches, axis=1)
                    # deferred sync AFTER the host-side sample/ship so that
                    # work overlaps the tail of the previous window's device
                    # compute (before_dispatch blocks on it — see PlayerSync)
                    player_params = psync.before_dispatch(player_params)
                    key, tk = jax.random.split(key)
                    if sentinel is not None:
                        if h_dev is None:
                            h_dev = sentinel.init_state()
                        h_dev, params, opt_state, last_losses = train_phase(
                            h_dev, params, opt_state, batches, tk,
                            jnp.int32(grad_step_counter),
                        )
                    else:
                        params, opt_state, last_losses = train_phase(
                            params, opt_state, batches, tk, jnp.int32(grad_step_counter)
                        )
                    grad_step_counter += due
                    player_params = psync.after_dispatch(params, player_params)

        # ---------------- training-health sentinel ---------------------------
        # the one D2H of the sentinel: a per-interval fetch of the tiny
        # HealthState, publishing Health/* through the hub and deciding
        # whether the divergence detector demands a rollback
        if (
            sentinel is not None
            and h_dev is not None
            and sentinel.should_poll(update, total_iters)
            and sentinel.poll(h_dev, policy_step) == "rollback"
        ):
            sentinel.begin_rollback(policy_step)  # raises past the budget
            rb_state, rb_dir = rollback_state(ckpt_mgr, fabric)
            if rb_state is None:
                raise DivergenceError(
                    f"training diverged at step {policy_step} with no committed "
                    "checkpoint to roll back to"
                )
            # restore exactly like a resume: params through the agent builder
            # (identical placement, so the guarded executable is reusable),
            # opt state/RNG streams replicated, grad-step counter rewound.
            # The replay buffer is NOT rolled back — transitions collected by
            # the diverged policy are still valid off-policy data.
            _, _, params = build_agent_fn(fabric, act_dim, cfg, obs_dim, rb_state["agent"])
            opt_state = fabric.replicate(rb_state["opt_state"])
            if rb_state.get("key") is not None:
                key = jnp.asarray(rb_state["key"])
            if rb_state.get("player_key") is not None:
                player_key = jax.device_put(jnp.asarray(rb_state["player_key"]), host)
            grad_step_counter = int(rb_state.get("grad_steps", grad_step_counter))
            counter_dev = None  # re-staged (replicated) before the next window
            h_dev = sentinel.reseed_state()  # diverged flag clears, dispatch count survives
            player_params = psync.init(params)
            last_losses = None
            fabric.print(
                f"health: diverged at step {policy_step} — rolled back to "
                f"committed snapshot {rb_dir}"
            )
            sentinel.rolled_back(policy_step, rb_dir)

        # ---------------- logging -------------------------------------------
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                vl, pl, al = last_losses
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/policy_loss", pl)
                aggregator.update("Loss/alpha_loss", al)
            last_log = flush_metrics(
                aggregator, timer, logger, policy_step, last_log,
                extra_metrics={
                    "Params/replay_ratio": grad_step_counter * fabric.world_size / max(policy_step, 1),
                    # deferred-sync staleness, made visible (ISSUE 12)
                    **psync.metrics(),
                },
            )

        # ---------------- checkpoint ----------------------------------------
        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "psync": psync.state_dict(),
                "grad_steps": grad_step_counter,
                "pending_gradient_steps": window.pending,
            }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    profiler.close()
    envs.close()
    if sentinel is not None:
        sentinel.close()
    if getattr(rb, "spill", None) is not None:
        rb.spill.close()
    ckpt_mgr.finalize()
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        # the deferred-sync (decoupled) player may be stale: sync once more
        player_params = psync.init(params)
        test(actor, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
