"""SAC evaluation entrypoint (reference: sheeprl/algos/sac/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["sac", "sac_decoupled"])
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    env = make_env(cfg, cfg.seed, 0)()
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_dim = int(sum(np.prod(env.observation_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(env.action_space.shape))
    env.close()
    actor, critic, params = build_agent(fabric, act_dim, cfg, obs_dim, state["agent"])
    test(actor, fabric.to_host(params["actor"]), cfg, log_dir, logger)
