"""SAC losses (reference: sheeprl/algos/sac/loss.py)."""

from __future__ import annotations

import jax


def critic_loss(qs: jax.Array, target: jax.Array) -> jax.Array:
    """Sum of per-critic MSEs; ``qs`` is (N, B), ``target`` (B,).  Plain mse
    per critic (no 0.5), matching the reference scale
    (reference: sheeprl/algos/sac/loss.py:15-20)."""
    return ((qs - target[None, :]) ** 2).mean(axis=1).sum()


def actor_loss(alpha: jax.Array, log_prob: jax.Array, min_q: jax.Array) -> jax.Array:
    return (alpha * log_prob - min_q).mean()


def alpha_loss(log_alpha: jax.Array, log_prob: jax.Array, target_entropy: float) -> jax.Array:
    """Eq. 17 temperature objective: gradient w.r.t. log_alpha is the mean
    entropy error, independent of alpha's current magnitude
    (reference: sheeprl/algos/sac/loss.py:23-26)."""
    return -(log_alpha * jax.lax.stop_gradient(log_prob + target_entropy)).mean()
