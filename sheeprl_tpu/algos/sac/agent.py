"""SAC agent (flax).

Capability parity with the reference agent
(reference: sheeprl/algos/sac/agent.py:1-371): squashed-Gaussian actor,
an ensemble of N Q-critics with EMA target copies, and a learnable
temperature ``log_alpha``.

TPU-first details:
* the critic ensemble is a ``flax.linen.vmap`` over parameters — all N
  Q-networks evaluate as ONE batched matmul stack on the MXU instead of N
  sequential module calls;
* the target network is just a second params pytree updated with a jitted
  EMA (`tau`), no module copies (reference: agent.py:256-268).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import MLP
from sheeprl_tpu.utils.distribution import TanhNormal

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


class SACActor(nn.Module):
    act_dim: int
    hidden_size: int = 256
    num_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(
            hidden_sizes=(self.hidden_size,) * self.num_layers,
            activation="relu",
            dtype=self.dtype,
            name="trunk",
        )(obs)
        mean = nn.Dense(self.act_dim, dtype=jnp.float32, name="mean")(x)
        log_std = nn.Dense(self.act_dim, dtype=jnp.float32, name="log_std")(x)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        return mean, log_std

    def dist(self, mean: jax.Array, log_std: jax.Array) -> TanhNormal:
        return TanhNormal(mean, jnp.exp(log_std))


class SACCriticEnsemble(nn.Module):
    """N Q-functions evaluated in parallel via params-vmap; output (N, B)."""

    n_critics: int = 2
    hidden_size: int = 256
    num_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)

        q_net = nn.vmap(
            MLP,
            in_axes=None,
            out_axes=0,
            axis_size=self.n_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        q = q_net(
            hidden_sizes=(self.hidden_size,) * self.num_layers,
            output_dim=1,
            activation="relu",
            dtype=self.dtype,
            name="q_ensemble",
        )(x)
        return q[..., 0]  # (N, B)


def sample_action(
    actor: SACActor, params: Any, obs: jax.Array, key: jax.Array, greedy: bool = False
) -> Tuple[jax.Array, jax.Array]:
    mean, log_std = actor.apply(params, obs)
    dist = TanhNormal(mean, jnp.exp(log_std))
    if greedy:
        return dist.mode(), jnp.zeros(mean.shape[:-1])
    return dist.sample_and_log_prob(key)


def ema_update(target: Any, online: Any, tau: float) -> Any:
    """Polyak averaging of target params (reference: agent.py:256-268)."""
    return jax.tree.map(lambda t, o: (1.0 - tau) * t + tau * o, target, online)


def build_agent(
    fabric: Any,
    act_dim: int,
    cfg: Any,
    obs_dim: int,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACActor, SACCriticEnsemble, Dict[str, Any]]:
    """Build actor/critic modules + a params dict
    {actor, critic, target_critic, log_alpha} (reference: agent.py:300-371)."""
    actor = SACActor(
        act_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        dtype=fabric.precision.compute_dtype,
    )
    critic = SACCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dtype=fabric.precision.compute_dtype,
    )
    if state is not None:
        params = state
    else:
        k1, k2 = jax.random.split(jax.random.PRNGKey(cfg.seed))
        dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        actor_params = actor.init(k1, dummy_obs)
        critic_params = critic.init(k2, dummy_obs, dummy_act)
        params = {
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "log_alpha": jnp.asarray(np.log(cfg.algo.alpha.alpha), jnp.float32),
        }
    return actor, critic, fabric.replicate(params)
