"""SAC, decoupled (player/trainer-overlapped) topology
(reference: sheeprl/algos/sac/sac_decoupled.py:32-588).

The reference splits rank-0 player from trainer ranks with TorchCollective
scatter/broadcast.  Single-controller equivalent: train dispatches are
asynchronous (the host never blocks on them), and the player's params
refresh only every ``algo.player.sync_every`` windows (10 in this
experiment's config) — the player interacts on stale weights while the
device trains, exactly the reference's player↔trainer weight-refresh
cadence without any process groups.
"""

from __future__ import annotations

from typing import Any

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import sac_loop
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm(decoupled=True, name="sac_decoupled")
def main(fabric: Any, cfg: Any) -> None:
    def plain_apply(critic, cp, o, a, k):
        return critic.apply(cp, o, a)

    sac_loop(fabric, cfg, build_agent, plain_apply)
