"""SAC, decoupled (player/trainer-overlapped) topology
(reference: sheeprl/algos/sac/sac_decoupled.py:32-588).

The reference splits rank-0 player from trainer ranks with TorchCollective
scatter/broadcast.  Two TPU-native realizations:

* single/multi-process pipelined (default): train dispatches are
  asynchronous (the host never blocks on them), and the player's params
  refresh only every ``algo.player.sync_every`` windows (10 in this
  experiment's config) — the player interacts on stale weights while the
  device trains, exactly the reference's player↔trainer weight-refresh
  cadence without any process groups.
* ``algo.player.dedicated=True`` with >= 2 processes: a REAL cross-process
  split — process 0 owns envs + replay buffer and samples the gradient
  blocks (the reference's player, sac_decoupled.py:250-280), processes
  1..N-1 train over a trainer sub-mesh; blocks travel player→trainers and
  actor weights travel back over host object collectives (DCN).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import make_sac_train_fns, sac_loop
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


@register_algorithm(decoupled=True, name="sac_decoupled")
def main(fabric: Any, cfg: Any) -> None:
    def plain_apply(critic, cp, o, a, k):
        return critic.apply(cp, o, a)

    from sheeprl_tpu.parallel.topology import resolve_topology

    topo_name = resolve_topology(cfg, fabric)
    if topo_name == "pod":
        # the cross-host actor/learner split (docs/distributed.md)
        from sheeprl_tpu.sebulba.pod import run_pod

        run_pod(fabric, cfg)
        return
    if topo_name == "sebulba":
        # the Sebulba actor/learner device split (docs/sebulba.md)
        from sheeprl_tpu.sebulba.sac import run_sebulba

        run_sebulba(fabric, cfg)
        return
    dedicated = (cfg.algo.get("player", {}) or {}).get("dedicated", False)
    if dedicated and fabric.num_processes > 1:
        # DEPRECATION SHIM: the two-rank split is superseded by the Sebulba
        # device split (topology=sebulba, docs/sebulba.md)
        import warnings

        warnings.warn(
            "algo.player.dedicated=True (the two-rank player/trainer split) "
            "is deprecated: use the Sebulba device split instead "
            "(topology=sebulba topology.actor_devices=K, docs/sebulba.md). "
            "The cross-process path still runs for now.",
            DeprecationWarning,
        )
        return _dedicated_main(fabric, cfg, plain_apply)
    if dedicated:
        import warnings

        warnings.warn(
            "algo.player.dedicated=True needs >= 2 processes (jax.distributed); "
            "falling back to the single-controller pipelined topology "
            "(deprecated — prefer topology=sebulba, docs/sebulba.md)",
            UserWarning,
        )
    sac_loop(fabric, cfg, build_agent, plain_apply)


def _dedicated_main(fabric: Any, cfg: Any, critic_apply: Any) -> None:
    """Cross-process player/trainer SAC (reference:
    sheeprl/algos/sac/sac_decoupled.py — player :32-345, trainer :348-545).

    Lockstep protocol: both sides run the same deterministic iteration
    skeleton (policy-step counters, ``Ratio`` schedule, checkpoint cadence)
    so they agree on WHEN a gradient block is broadcast [sync A], when
    refreshed actor weights come back [sync B, every
    ``algo.player.sync_every`` training windows, one window stale — the
    reference's refresh cadence], and when a full-state checkpoint
    rendezvous happens [sync C], without any control messages.
    """
    rank = fabric.global_rank
    is_player = rank == 0
    key = fabric.seed_everything(cfg.seed)
    if is_player:
        # fork the player's key stream off the trainers' (the coupled path's
        # fold_in(rank) separation)
        key = jax.random.fold_in(key, 0x9E37)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    # commit-protocol/async saves via the manager; cadence stays the shared
    # deterministic rule below, and preemption is NOT polled — the lockstep
    # player↔trainer broadcasts cannot tolerate one rank breaking out
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if is_player:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = None
    if is_player:
        envs = vectorize(
            cfg,
            [
                make_env(cfg, cfg.seed + i, 0, run_name=log_dir, vector_env_idx=i)
                for i in range(num_envs)
            ],
        )
        spaces = (envs.single_observation_space, envs.single_action_space)
    else:
        spaces = None
    obs_space, act_space = fabric.broadcast_object(spaces, src=0)
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only, like the reference")
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    for k in mlp_keys:
        if k not in obs_space.spaces:
            raise ValueError(f"mlp key '{k}' not in observation space {list(obs_space.spaces)}")
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))
    act_low = np.asarray(act_space.low, np.float32)
    act_high = np.asarray(act_space.high, np.float32)

    def to_env_actions(a: np.ndarray) -> np.ndarray:
        return act_low + (a + 1.0) * 0.5 * (act_high - act_low)

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        # only the player touches the checkpoint file: trainers receive the
        # state WITHOUT the replay buffer (which can be multi-GB and is
        # player-only) instead of each transiently unpickling all of it
        if is_player:
            state = fabric.load(cfg.checkpoint.resume_from)
            lean = {k: v for k, v in state.items() if k != "rb"}
        else:
            lean = None
        lean = fabric.broadcast_object(lean, src=0)
        if not is_player:
            state = lean

    from sheeprl_tpu.parallel.fabric import (
        get_single_device_fabric,
        get_trainer_fabric,
        trainer_device_count,
    )

    # honor algo.player.device (host by default; 'accelerator' = the player
    # process's own otherwise-idle chip, for big pixel encoders)
    host = fabric.player_device(cfg)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)

    if is_player:
        player_fabric = get_single_device_fabric(fabric, device=host)
        actor, critic, params = build_agent(player_fabric, act_dim, cfg, obs_dim, state.get("agent"))
        player_params = fabric.copy_to(params["actor"], host)
        trainer_fabric = None
        t_world = trainer_device_count(fabric, player_process=0)
    else:
        trainer_fabric = get_trainer_fabric(fabric, player_process=0)
        t_world = trainer_fabric.world_size
        actor, critic, params = build_agent(trainer_fabric, act_dim, cfg, obs_dim, state.get("agent"))
        opt_state = trainer_fabric.replicate(
            state.get("opt_state")
            or {
                "actor": actor_opt.init(params["actor"]),
                "critic": critic_opt.init(params["critic"]),
                "alpha": alpha_opt.init(params["log_alpha"]),
            }
        )

    act_fn, train_phase = make_sac_train_fns(
        actor, critic, critic_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    # ---------------- deterministic lockstep counters ------------------------
    policy_steps_per_iter = num_envs  # only the player steps envs
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    learning_starts = int(cfg.algo.learning_starts) // policy_steps_per_iter if not cfg.dry_run else 0
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    if state:
        learning_starts += start_iter
    sync_every = max(1, int((cfg.algo.get("player", {}) or {}).get("sync_every", 1)))
    windows = int(state.get("windows", 0))

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    batch_size = int(cfg.algo.per_rank_batch_size) * max(t_world, 1)

    rb = None
    if is_player:
        rb = ReplayBuffer(
            int(cfg.buffer.size) // num_envs,
            num_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", "rank_0") if cfg.buffer.memmap else None,
        )
        if state and cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])

    # ---------------- trainer-side batch assembly ----------------------------
    if not is_player:
        from sheeprl_tpu.parallel.fabric import host_tree_to_mesh

        tmesh = trainer_fabric.mesh

        def to_mesh(tree, axis=1):
            # batch_size = per_rank_batch_size * t_world by construction, so
            # the batch axis always divides the trainer mesh
            return host_tree_to_mesh(tree, tmesh, axis=axis, shard=True)

    from sheeprl_tpu.parallel.fabric import fetch_local as fetch

    # ---------------- main loop ----------------------------------------------
    acc_train_times: Dict[str, float] = {}
    obs_vec = None
    if is_player:
        obs, _ = envs.reset(seed=cfg.seed)
        obs_vec = np.asarray(prepare_obs(obs, mlp_keys))
    last_losses = None
    # player key stream advanced inside act_fn (single player process: no
    # rank folding needed — only process 0 steps envs)
    player_key = jax.random.fold_in(key, 1023) if is_player else None

    for update in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        if is_player:
            with timer("Time/env_interaction_time"):
                if update <= learning_starts and not state:
                    env_actions = np.stack([act_space.sample() for _ in range(num_envs)])
                    span = act_high - act_low
                    actions = np.clip(2.0 * (env_actions - act_low) / np.where(span == 0, 1, span) - 1.0, -1, 1)
                else:
                    with jax.default_device(host):
                        a, player_key = act_fn(player_params, jnp.asarray(obs_vec), player_key)
                        actions = np.asarray(a)
                    env_actions = to_env_actions(actions)
                next_obs, rewards, terminated, truncated, info = envs.step(env_actions)
                dones = np.logical_or(terminated, truncated).astype(np.float32)
                rewards = np.asarray(rewards, np.float32)
                next_vec = np.asarray(prepare_obs(next_obs, mlp_keys))
                store_next = next_vec
                done_idx = np.nonzero(dones)[0]
                if done_idx.size:
                    final = final_obs_rows(info, done_idx, mlp_keys)
                    if final is not None:
                        store_next = next_vec.copy()
                        store_next[done_idx] = np.concatenate(
                            [np.asarray(final[k], np.float32).reshape(done_idx.size, -1) for k in mlp_keys],
                            axis=-1,
                        )
                rb.add(
                    {
                        "obs": obs_vec[None],
                        "next_obs": store_next[None],
                        "actions": actions[None].astype(np.float32),
                        "rewards": rewards[None, :, None],
                        "terminated": terminated.astype(np.float32)[None, :, None],
                    }
                )
                obs_vec = next_vec
                for ep_ret, ep_len in episode_stats(info):
                    aggregator.update("Rewards/rew_avg", ep_ret)
                    aggregator.update("Game/ep_len_avg", ep_len)
        # ---------------- training windows (lockstep) ------------------------
        if update >= learning_starts:
            gradient_steps = ratio(policy_step / max(t_world, 1))
            if gradient_steps > 0:
                windows += 1
                sync_due = windows % sync_every == 0
                if is_player:
                    sample = rb.sample(batch_size, n_samples=gradient_steps)
                    block = {
                        "obs": np.asarray(sample["obs"], np.float32),
                        "next_obs": np.asarray(sample["next_obs"], np.float32),
                        "actions": np.asarray(sample["actions"], np.float32),
                        "rewards": np.asarray(sample["rewards"][..., 0], np.float32),
                        "terminated": np.asarray(sample["terminated"][..., 0], np.float32),
                    }
                else:
                    block = None
                block = fabric.broadcast_object(block, src=0)  # sync A
                key, tk = jax.random.split(key)
                back = None
                if not is_player:
                    if sync_due and rank == 1:
                        # PREVIOUS window's (long since finished) weights —
                        # fetched before this window's dispatch donates them
                        back = (
                            fetch(params["actor"]),
                            fetch(last_losses) if last_losses is not None else None,
                            timer.to_dict(reset=True),
                        )
                    with timer("Time/train_time"):
                        params, opt_state, last_losses = train_phase(
                            params, opt_state, to_mesh(block), tk, jnp.int32(grad_step_counter)
                        )
                grad_step_counter += gradient_steps
                if sync_due:
                    back = fabric.broadcast_object(back, src=1)  # sync B
                    if is_player:
                        actor_np, losses_np, t_times = back
                        player_params = jax.device_put(actor_np, host)
                        if losses_np is not None:
                            last_losses = losses_np
                        for tk_, tv_ in (t_times or {}).items():
                            acc_train_times[tk_] = acc_train_times.get(tk_, 0.0) + tv_

        # ---------------- logging (player) -----------------------------------
        if is_player and cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                vl, pl, al = last_losses
                aggregator.update("Loss/value_loss", float(vl))
                aggregator.update("Loss/policy_loss", float(pl))
                aggregator.update("Loss/alpha_loss", float(al))
            last_log = flush_metrics(
                aggregator, timer, logger, policy_step, last_log,
                extra_times=dict(acc_train_times),
                extra_metrics={"Params/replay_ratio": grad_step_counter * max(t_world, 1) / max(policy_step, 1)},
            )
            acc_train_times.clear()

        # ---------------- checkpoint rendezvous [sync C] ----------------------
        if (
            cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every
        ) or (update == total_iters and cfg.checkpoint.save_last):
            last_checkpoint = policy_step
            payload = None
            if rank == 1:
                payload = (fetch(params), fetch(opt_state))
            payload = fabric.broadcast_object(payload, src=1)
            agent_np, opt_np = payload
            ckpt_state = {
                "agent": agent_np,
                "opt_state": opt_np,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "grad_steps": grad_step_counter,
                "windows": windows,
            }
            # every process calls the hook: fabric.save writes on the player
            # (global zero, which owns the buffer) and barriers everyone;
            # keep_last pruning applies
            fabric.call(
                "on_checkpoint_player",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt"),
                state=ckpt_state,
                replay_buffer=rb if (is_player and cfg.buffer.checkpoint) else None,
            )

    # final resync: player_params lag by up to sync_every windows (the
    # coupled loop's psync.init-before-test, sac.py, does the same job)
    final_actor = fabric.broadcast_object(fetch(params["actor"]) if rank == 1 else None, src=1)
    if is_player:
        player_params = jax.device_put(final_actor, host)
        envs.close()
        if cfg.algo.run_test:
            test(actor, player_params, cfg, log_dir, logger)
    ckpt_mgr.finalize()
    if logger is not None:
        logger.close()
