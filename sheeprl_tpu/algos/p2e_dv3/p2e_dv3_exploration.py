"""Plan2Explore over DreamerV3 — exploration phase
(reference: sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py:1-1059).

On top of the DreamerV3 world model:
* an ensemble of N forward models predicting the next posterior state from
  (latent ⊕ action), trained with MSE (reference: :207-230);
* intrinsic reward = ensemble-prediction variance × multiplier (:262-287);
* a DICT of exploration critics (intrinsic + extrinsic), each with its own
  target network, Moments normalizer and advantage weight — the exploration
  actor maximizes the weight-normalized advantage sum (:234-330);
* the TASK actor/critic train on extrinsic rewards in parallel so the
  finetuning phase starts from a task policy.

The environment player acts with the exploration actor
(``algo.player.actor_type``).  All of it runs inside the same
single-dispatch scanned train phase as the rest of the Dreamer family.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    Critic,
    DreamerMLP,
    WorldModel,
    build_agent as dv3_build_agent,
)
from sheeprl_tpu.algos.dreamer_v3.loss import world_model_loss
from sheeprl_tpu.algos.p2e_utils import ensemble_disagreement
from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values, normalize_obs_block, moments_update
from sheeprl_tpu.utils.distribution import Bernoulli, OneHotCategorical, TwoHotEncodingDistribution
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import window_scan


def build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state=None):
    """DV3 agent + ensembles + exploration actor + per-reward critics."""
    world_model, actor, critic, params = dv3_build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space,
        state if state is not None else None,
    )
    if state is not None:
        return world_model, actor, critic, params

    params = jax.device_get(params)
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    rec = wm_cfg.recurrent_model.recurrent_state_size
    latent_dim = stoch_flat + rec
    act_width = int(sum(actions_dim))
    key = jax.random.PRNGKey(cfg.seed + 1)
    k_ens, k_actor, *k_crit = jax.random.split(key, 3 + len(cfg.algo.critics_exploration))

    ens = ensemble_module(cfg)
    ens_params = ens.init(k_ens, jnp.zeros((1, latent_dim + act_width)))

    # exploration actor (same class as the task actor)
    dummy_latent = jnp.zeros((1, latent_dim))
    actor_expl_params = actor.init(k_actor, dummy_latent)

    critics_expl: Dict[str, Any] = {}
    for kc, name in zip(k_crit, cfg.algo.critics_exploration):
        cp = critic.init(kc, dummy_latent)
        critics_expl[name] = {
            "critic": cp,
            "target": jax.tree.map(jnp.copy, cp),
            "moments": {"low": jnp.zeros(()), "high": jnp.zeros(())},
        }

    # task actor/critic are the dv3-built ones; the PLAYER uses "actor",
    # which is the exploration actor during this phase
    params = {
        **params,
        "actor_task": params["actor"],
        "actor": actor_expl_params,
        "ensembles": ens_params,
        "critics_exploration": critics_expl,
    }
    return world_model, actor, critic, fabric.replicate(params)


def ensemble_module(cfg):
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size

    class Ensembles(nn.Module):
        """N forward models as one params-vmapped MLP stack (MXU-batched)."""

        @nn.compact
        def __call__(self, x):
            net = nn.vmap(
                DreamerMLP,
                in_axes=None,
                out_axes=0,
                axis_size=int(cfg.algo.ensembles.n),
                variable_axes={"params": 0},
                split_rngs={"params": True},
            )
            return net(
                units=cfg.algo.ensembles.dense_units,
                layers=cfg.algo.ensembles.mlp_layers,
                output_dim=stoch_flat,
                act=cfg.algo.dense_act,
                name="ens",
            )(x)  # (N, ..., stoch_flat)

    return Ensembles()


def make_train_phase(fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
                     cnn_keys, mlp_keys, is_continuous, params=None, opt_state=None):
    """DV3 world-model update + ensemble update + dual-critic exploration
    behavior + task behavior, scanned over the update block."""

    obs_keys = tuple(cnn_keys) + tuple(mlp_keys)
    stoch_flat = world_model.stoch_flat
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    tau = float(cfg.algo.critic.tau)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    ent_coef = float(cfg.algo.actor.ent_coef)
    moments_cfg = cfg.algo.actor.moments
    intrinsic_mult = float(cfg.algo.intrinsic_reward_multiplier)
    critics_cfg = cfg.algo.critics_exploration
    ens = ensemble_module(cfg)
    ens_opt = build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)

    wm_loss_cfg = dict(
        kl_dynamic=float(cfg.algo.world_model.kl_dynamic),
        kl_representation=float(cfg.algo.world_model.kl_representation),
        kl_free_nats=float(cfg.algo.world_model.kl_free_nats),
        kl_regularizer=float(cfg.algo.world_model.kl_regularizer),
        continue_scale_factor=float(cfg.algo.world_model.continue_scale_factor),
    )

    from sheeprl_tpu.utils.distribution import MSEDistribution, SymlogDistribution

    def wm_forward(wm_params, data, k):
        L, B = data["rewards"].shape
        obs = normalize_obs_block(data, cnn_keys, obs_keys)
        flat_obs = {kk: v.reshape((L * B,) + v.shape[2:]) for kk, v in obs.items()}
        embed = world_model.apply(wm_params, flat_obs, method=WorldModel.encode).reshape(L, B, -1)
        actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)
        is_first = data["is_first"].at[0].set(1.0)[..., None]

        def step(carry, xs):
            h, z = carry
            embed_t, act_t, first_t, k_t = xs
            h, z, post_logits, prior_logits = world_model.apply(
                wm_params, h, z, act_t, embed_t, first_t, k_t, method=WorldModel.dynamic
            )
            return (h, z), (h, z, post_logits, prior_logits)

        keys = jax.random.split(k, L)
        _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(
            step, (jnp.zeros((B, rec_size)), jnp.zeros((B, stoch_flat))),
            (embed, actions, is_first, keys),
        )
        latents = jnp.concatenate([zs, hs], -1)
        flat_latents = latents.reshape(L * B, -1)
        recon = world_model.apply(wm_params, flat_latents, method=WorldModel.decode)
        obs_log_probs = {}
        for kk in cnn_keys:
            obs_log_probs[kk] = MSEDistribution(recon[kk].reshape(obs[kk].shape), event_dims=3).log_prob(obs[kk])
        for kk in mlp_keys:
            obs_log_probs[kk] = SymlogDistribution(recon[kk].reshape(L, B, -1), event_dims=1).log_prob(obs[kk])
        reward_logits = world_model.apply(wm_params, flat_latents, method=WorldModel.reward_logits)
        reward_lp = TwoHotEncodingDistribution(reward_logits.reshape(L, B, -1), dims=1).log_prob(
            data["rewards"][..., None]
        )
        cont_logits = world_model.apply(wm_params, flat_latents, method=WorldModel.continue_logits)
        cont_lp = Bernoulli(cont_logits.reshape(L, B)).log_prob(1.0 - data["terminated"])
        loss, aux = world_model_loss(obs_log_probs, reward_lp, cont_lp, post_logits, prior_logits, **wm_loss_cfg)
        aux["latents"] = latents
        aux["zs"] = zs
        aux["post_logits"] = post_logits
        aux["prior_logits"] = prior_logits
        return loss, aux

    def imagination_rollout(wm_params, actor_params, start_latents, k):
        def img_step(carry, k_t):
            h, z = carry
            latent = jnp.concatenate([z, h], -1)
            k_a, k_z = jax.random.split(k_t)
            head = actor.apply(actor_params, jax.lax.stop_gradient(latent))
            action = actor.sample(head, k_a)
            h, z = world_model.apply(wm_params, h, z, action, k_z, method=WorldModel.imagination)
            return (h, z), (latent, action)

        keys = jax.random.split(k, horizon + 1)
        _, (traj, actions_seq) = jax.lax.scan(
            img_step, (start_latents[:, stoch_flat:], start_latents[:, :stoch_flat]), keys
        )
        return traj, actions_seq

    def critic_mean(critic_params, flat):
        return TwoHotEncodingDistribution(
            critic.apply(critic_params, flat).reshape(horizon + 1, -1, cfg.algo.critic.bins), dims=1
        ).mean[..., 0]

    def exploration_actor_update(p, o_state, latents, terminated, k):
        n = latents.shape[0] * latents.shape[1]
        start = jax.lax.stop_gradient(latents.reshape(n, -1))
        weights_sum = sum(float(c["weight"]) for c in critics_cfg.values())

        def actor_loss_fn(actor_params):
            traj, actions_seq = imagination_rollout(p["world_model"], actor_params, start, k)
            flat_traj = traj.reshape((horizon + 1) * n, -1)
            continues = Bernoulli(
                world_model.apply(p["world_model"], flat_traj, method=WorldModel.continue_logits)
                .reshape(horizon + 1, n)
            ).mode()
            true_continue = (1.0 - terminated).reshape(1, n)
            continues = jnp.concatenate([true_continue, continues[1:]], 0)
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, 0) / gamma)

            # intrinsic reward: ensemble disagreement over next-state predictions
            ens_in = jnp.concatenate(
                [jax.lax.stop_gradient(traj), jax.lax.stop_gradient(actions_seq)], -1
            )
            preds = ens.apply(p["ensembles"], ens_in.reshape((horizon + 1) * n, -1))
            preds = preds.reshape(int(cfg.algo.ensembles.n), horizon + 1, n, stoch_flat)
            intrinsic = ensemble_disagreement(preds, intrinsic_mult)  # (H+1, n)

            advantage = 0.0
            aux_per_critic = {}
            for name, ccfg in critics_cfg.items():
                cstate = p["critics_exploration"][name]
                values = critic_mean(cstate["critic"], flat_traj)
                if ccfg["reward_type"] == "intrinsic":
                    reward = intrinsic
                else:
                    reward = TwoHotEncodingDistribution(
                        world_model.apply(p["world_model"], flat_traj, method=WorldModel.reward_logits)
                        .reshape(horizon + 1, n, -1),
                        dims=1,
                    ).mean[..., 0]
                lam = compute_lambda_values(reward[1:], values[1:], continues[1:] * gamma, lmbda)
                new_moments, offset, invscale = moments_update(
                    cstate["moments"], lam,
                    decay=float(moments_cfg.decay), max_=float(moments_cfg.max),
                    plow=float(moments_cfg.percentile.low), phigh=float(moments_cfg.percentile.high),
                )
                adv = ((lam - offset) / invscale) - ((values[:-1] - offset) / invscale)
                advantage = advantage + adv * float(ccfg["weight"]) / weights_sum
                aux_per_critic[name] = (lam, new_moments)

            heads = actor.apply(actor_params, jax.lax.stop_gradient(traj))
            if is_continuous:
                objective = advantage
            else:
                lp = actor.log_prob(heads[:-1], jax.lax.stop_gradient(actions_seq[:-1]))
                objective = lp * jax.lax.stop_gradient(advantage)
            entropy = actor.entropy(heads[:-1])
            loss = -jnp.mean(discount[:-1] * (objective + ent_coef * entropy))
            return loss, (traj, discount, intrinsic.mean(), aux_per_critic)

        (pl, (traj, discount, mean_intr, aux_pc)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(p["actor"])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state["actor"], p["actor"])
        p = {**p, "actor": optax.apply_updates(p["actor"], a_updates)}
        o_state = {**o_state, "actor": new_a_opt}

        # per-critic regression on its own λ-targets
        traj_sg = jax.lax.stop_gradient(traj[:-1])
        flat_sg = traj_sg.reshape(horizon * traj_sg.shape[1], -1)
        new_critics = {}
        vls = 0.0
        for name in critics_cfg:
            lam, new_moments = aux_pc[name]
            cstate = p["critics_exploration"][name]
            target_mean = TwoHotEncodingDistribution(
                critic.apply(cstate["target"], flat_sg).reshape(horizon, -1, cfg.algo.critic.bins),
                dims=1,
            ).mean

            def c_loss(cp):
                qv = TwoHotEncodingDistribution(
                    critic.apply(cp, flat_sg).reshape(horizon, -1, cfg.algo.critic.bins), dims=1
                )
                vl = -qv.log_prob(jax.lax.stop_gradient(lam)[..., None])
                vl = vl - qv.log_prob(jax.lax.stop_gradient(target_mean))
                return jnp.mean(vl * discount[:-1])

            vl, c_grads = jax.value_and_grad(c_loss)(cstate["critic"])
            c_updates, new_c_opt = critic_opt.update(
                c_grads, o_state["critics_exploration"][name], cstate["critic"]
            )
            new_cp = optax.apply_updates(cstate["critic"], c_updates)
            new_critics[name] = {
                "critic": new_cp,
                "target": jax.tree.map(lambda t, o: (1 - tau) * t + tau * o, cstate["target"], new_cp),
                "moments": new_moments,
            }
            o_state = {
                **o_state,
                "critics_exploration": {**o_state["critics_exploration"], name: new_c_opt},
            }
            vls = vls + vl
        p = {**p, "critics_exploration": new_critics}
        return p, o_state, pl, vls, mean_intr

    # task behavior: standard DV3 actor/critic update on extrinsic rewards
    def task_behavior_update(p, o_state, latents, terminated, k):
        n = latents.shape[0] * latents.shape[1]
        start = jax.lax.stop_gradient(latents.reshape(n, -1))

        def actor_loss_fn(actor_params):
            traj, actions_seq = imagination_rollout(p["world_model"], actor_params, start, k)
            flat_traj = traj.reshape((horizon + 1) * n, -1)
            rewards = TwoHotEncodingDistribution(
                world_model.apply(p["world_model"], flat_traj, method=WorldModel.reward_logits)
                .reshape(horizon + 1, n, -1),
                dims=1,
            ).mean[..., 0]
            values = critic_mean(p["critic"], flat_traj)
            continues = Bernoulli(
                world_model.apply(p["world_model"], flat_traj, method=WorldModel.continue_logits)
                .reshape(horizon + 1, n)
            ).mode()
            true_continue = (1.0 - terminated).reshape(1, n)
            continues = jnp.concatenate([true_continue, continues[1:]], 0)
            lam = compute_lambda_values(rewards[1:], values[1:], continues[1:] * gamma, lmbda)
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, 0) / gamma)
            new_moments, offset, invscale = moments_update(
                p["moments"], lam,
                decay=float(moments_cfg.decay), max_=float(moments_cfg.max),
                plow=float(moments_cfg.percentile.low), phigh=float(moments_cfg.percentile.high),
            )
            adv = ((lam - offset) / invscale) - ((values[:-1] - offset) / invscale)
            heads = actor.apply(actor_params, jax.lax.stop_gradient(traj))
            if is_continuous:
                objective = adv
            else:
                lp = actor.log_prob(heads[:-1], jax.lax.stop_gradient(actions_seq[:-1]))
                objective = lp * jax.lax.stop_gradient(adv)
            entropy = actor.entropy(heads[:-1])
            loss = -jnp.mean(discount[:-1] * (objective + ent_coef * entropy))
            return loss, (traj, lam, discount, new_moments)

        (pl, (traj, lam, discount, new_moments)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(p["actor_task"])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state["actor_task"], p["actor_task"])
        p = {**p, "actor_task": optax.apply_updates(p["actor_task"], a_updates), "moments": new_moments}

        traj_sg = jax.lax.stop_gradient(traj[:-1])
        flat_sg = traj_sg.reshape(horizon * traj_sg.shape[1], -1)
        target_mean = TwoHotEncodingDistribution(
            critic.apply(p["target_critic"], flat_sg).reshape(horizon, -1, cfg.algo.critic.bins), dims=1
        ).mean

        def c_loss(cp):
            qv = TwoHotEncodingDistribution(
                critic.apply(cp, flat_sg).reshape(horizon, -1, cfg.algo.critic.bins), dims=1
            )
            vl = -qv.log_prob(jax.lax.stop_gradient(lam)[..., None])
            vl = vl - qv.log_prob(jax.lax.stop_gradient(target_mean))
            return jnp.mean(vl * discount[:-1])

        vl, c_grads = jax.value_and_grad(c_loss)(p["critic"])
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state["critic"], p["critic"])
        p = {**p, "critic": optax.apply_updates(p["critic"], c_updates)}
        return p, {**o_state, "actor_task": new_a_opt, "critic": new_c_opt}, pl, vl

    def single_update(carry, inputs):
        p, o_state, counter = carry
        data, k = inputs
        k_wm, k_ens, k_expl, k_task = jax.random.split(k, 4)

        (wm_l, aux), wm_grads = jax.value_and_grad(wm_forward, has_aux=True)(
            p["world_model"], data, k_wm
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, o_state["world_model"], p["world_model"])
        p = {**p, "world_model": optax.apply_updates(p["world_model"], wm_updates)}
        o_state = {**o_state, "world_model": new_wm_opt}

        # ensembles: predict next posterior from (latent, action) (ref :207-230)
        latents = aux["latents"]
        zs = aux["zs"]
        L, B = data["rewards"].shape

        def ens_loss(ep):
            inp = jnp.concatenate(
                [jax.lax.stop_gradient(latents), jax.lax.stop_gradient(data["actions"])], -1
            )[:-1]
            preds = ens.apply(ep, inp.reshape((L - 1) * B, -1))
            target = jax.lax.stop_gradient(zs[1:]).reshape(1, (L - 1) * B, -1)
            return jnp.mean((preds.reshape(int(cfg.algo.ensembles.n), (L - 1) * B, -1) - target) ** 2)

        el, e_grads = jax.value_and_grad(ens_loss)(p["ensembles"])
        e_updates, new_e_opt = ens_opt.update(e_grads, o_state["ensembles"], p["ensembles"])
        p = {**p, "ensembles": optax.apply_updates(p["ensembles"], e_updates)}
        o_state = {**o_state, "ensembles": new_e_opt}

        p, o_state, pl_e, vl_e, mean_intr = exploration_actor_update(
            p, o_state, latents, data["terminated"], k_expl
        )
        p, o_state, pl_t, vl_t = task_behavior_update(
            p, o_state, latents, data["terminated"], k_task
        )

        do_ema = (counter % target_freq) == 0
        new_target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o, p["target_critic"], p["critic"])
        p = {
            **p,
            "target_critic": jax.tree.map(
                lambda n_, o_: jnp.where(do_ema, n_, o_), new_target, p["target_critic"]
            ),
        }
        post_ent = OneHotCategorical(jax.lax.stop_gradient(aux["post_logits"])).entropy().sum(-1).mean()
        prior_ent = OneHotCategorical(jax.lax.stop_gradient(aux["prior_logits"])).entropy().sum(-1).mean()
        metrics = (
            wm_l, aux["observation_loss"], aux["reward_loss"], aux["kl_loss"],
            aux["continue_loss"], aux["kl"], pl_e + pl_t, vl_e + vl_t, post_ent, prior_ent,
        )
        return (p, o_state, counter + 1), metrics

    def train_phase(p, o_state, blocks, k, counter0):
        U = blocks["rewards"].shape[0]
        keys = jax.random.split(k, U)
        (p, o_state, _), metrics = window_scan(
            single_update, (p, o_state, counter0), (blocks, keys), unroll=bool(cnn_keys)
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), metrics)

    in_sh = out_sh = None
    if params is not None and opt_state is not None:
        from sheeprl_tpu.parallel.compile import state_io_shardings
        from sheeprl_tpu.parallel.sharding import shardings_of

        in_sh, out_sh = state_io_shardings(
            shardings_of(params), shardings_of(opt_state), n_extra_in=3, n_extra_out=1
        )
    return fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        in_shardings=in_sh,
        out_shardings=out_sh,
        max_recompiles=cfg.algo.get("max_recompiles"),
    )


def build_p2e_optimizers(fabric, cfg, params, saved=None):
    wm_opt = build_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ens_opt = build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)
    opt_state = fabric.replicate(
        saved
        or {
            "world_model": wm_opt.init(params["world_model"]),
            "actor": actor_opt.init(params["actor"]),
            "actor_task": actor_opt.init(params["actor_task"]),
            "critic": critic_opt.init(params["critic"]),
            "ensembles": ens_opt.init(params["ensembles"]),
            "critics_exploration": {
                name: critic_opt.init(c["critic"])
                for name, c in params["critics_exploration"].items()
            },
        }
    )
    return wm_opt, actor_opt, critic_opt, opt_state


@register_algorithm(name="p2e_dv3_exploration")
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    dreamer_family_loop(
        fabric, cfg, build_agent, make_train_phase, optimizer_builder=build_p2e_optimizers
    )
