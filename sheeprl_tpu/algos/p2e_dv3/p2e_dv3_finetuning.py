"""Plan2Explore over DreamerV3 — finetuning phase
(reference: sheeprl/algos/p2e_dv3/p2e_dv3_finetuning.py:28-477).

Reloads the exploration phase's checkpoint — world model, TASK actor/critic
(and optionally the replay buffer) — and continues with standard DreamerV3
training on the task reward.  The reference implements the config
inheritance in the CLI (reference: sheeprl/cli.py:117-148); here the
exploration checkpoint is given via ``checkpoint.exploration_ckpt_path``.
"""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent as dv3_build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
    dreamer_family_loop,
    make_train_phase as dv3_make_train_phase,
)
from sheeprl_tpu.algos.p2e_utils import actor_type_from_cfg, project_exploration_state
from sheeprl_tpu.config.compose import ConfigError
from sheeprl_tpu.utils.registry import register_algorithm


def exploration_state_to_dv3(state: Dict[str, Any], actor_type: str = "task") -> Dict[str, Any]:
    """Project an exploration-phase checkpoint onto the DV3 state layout."""
    return project_exploration_state(
        state, actor_type,
        keep_keys=("world_model", "critic", "target_critic"),
        defaults={"moments": {"low": 0.0, "high": 0.0}},
    )


@register_algorithm(name="p2e_dv3_finetuning")
def main(fabric: Any, cfg: Any) -> None:
    ckpt_path = cfg.checkpoint.get("exploration_ckpt_path")
    initial_state = None
    if ckpt_path:
        raw = fabric.load(ckpt_path)
        initial_state = exploration_state_to_dv3(raw, actor_type=actor_type_from_cfg(cfg))
        if not cfg.buffer.get("load_from_exploration", False):
            initial_state.pop("rb", None)
    elif not cfg.checkpoint.resume_from:
        raise ConfigError(
            "p2e finetuning needs checkpoint.exploration_ckpt_path "
            "(or checkpoint.resume_from for a finetuning restart)"
        )
    dreamer_family_loop(
        fabric, cfg, dv3_build_agent, dv3_make_train_phase, initial_state=initial_state
    )
