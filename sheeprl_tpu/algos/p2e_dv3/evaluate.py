"""P2E DV3 evaluation (reference: sheeprl/algos/p2e_dv3/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.evaluate import _evaluate_dreamer
from sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration import build_agent
from sheeprl_tpu.algos.p2e_utils import choose_actor
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv3_exploration", "p2e_dv3_finetuning"], name="p2e_dv3")
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    agent = choose_actor(state["agent"], cfg)
    if "moments" not in agent:
        from sheeprl_tpu.algos.dreamer_v3.agent import build_agent as dv3_build

        return _evaluate_dreamer(fabric, cfg, {"agent": agent}, dv3_build)
    _evaluate_dreamer(fabric, cfg, {"agent": agent}, build_agent)
