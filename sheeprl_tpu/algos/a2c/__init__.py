"""A2C — TPU-native implementation."""
