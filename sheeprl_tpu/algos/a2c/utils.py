"""A2C support utilities (reference: sheeprl/algos/a2c/utils.py)."""

from sheeprl_tpu.algos.ppo.utils import (  # noqa: F401 — same obs/test machinery
    actions_for_env,
    prepare_obs,
    spaces_to_dims,
    test,
)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}
