"""A2C, coupled topology.

Capability parity with the reference (reference: sheeprl/algos/a2c/a2c.py:117-440):
on-policy rollouts, GAE, one synchronized gradient step per rollout.

The reference accumulates gradients across minibatches under
``fabric.no_backward_sync`` so DDP all-reduces once per update
(reference: a2c.py:53-116).  Gradient accumulation is a workaround for
framework overhead, not an algorithmic feature — on TPU the mathematically
identical thing is ONE jitted full-batch update per rollout (summed losses,
single XLA-inserted gradient all-reduce), which is also the fastest mapping
to the MXU.  Agent/encoder/player machinery is shared with PPO
(sheeprl_tpu/algos/ppo/agent.py) — same module family in the reference too.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, sample_actions
from sheeprl_tpu.algos.ppo.utils import (
    actions_for_env,
    normalize_obs_keys,
    obs_to_np,
    prepare_obs,
    spaces_to_dims,
    test,
)
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import stage_rollout, steady_guard
from sheeprl_tpu.envs.jax.registry import anakin_enabled
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, polynomial_decay, save_configs


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    use_anakin = anakin_enabled(cfg, fabric)
    # population mode (docs/population.md): vmap whole agents over a
    # population axis INSIDE the fused Anakin executable, with in-trace PBT
    pop_size = int(cfg.get("population", {}).get("size", 0) or 0)
    use_population = pop_size > 1
    if use_population and not use_anakin:
        raise ValueError(
            "population.size>1 rides the Anakin axis: it needs a pure-JAX env "
            "(env=jax_*), algo.anakin != False, and a single-process run"
        )
    if use_anakin:
        # Anakin mode (envs/jax/anakin.py): the env lives INSIDE the
        # compiled update — no vector-env processes exist at all
        from sheeprl_tpu.envs.jax.core import VectorJaxEnv
        from sheeprl_tpu.envs.jax.registry import jax_env_from_cfg

        envs = None
        venv = VectorJaxEnv(jax_env_from_cfg(cfg), num_envs)
        obs_space = venv.single_observation_space
        act_space = venv.single_action_space
    else:
        envs = vectorize(
            cfg,
            [
                make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
                for i in range(num_envs)
            ],
        )
        obs_space = envs.single_observation_space
        act_space = envs.single_action_space
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space,
        # population checkpoints hold STACKED (P, ...) params — restored in
        # the population block below, not through the single-agent loader
        None if (use_population and state) else state.get("agent"),
    )
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    if use_population:
        opt_state = None  # stacked per-member init happens in the population block
    else:
        opt_state = fabric.replicate(state.get("opt_state") or optimizer.init(params))

    aggregator = MetricAggregator(
        cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {}
    )
    timer.configure(cfg.metric)

    # on-policy loops honor algo.player.device (placement only; the sync
    # cadence options are meaningless on-policy: rollouts must use the
    # current weights)
    host = fabric.player_device(cfg)
    reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    ent_coef = float(cfg.algo.ent_coef)
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)

    def policy_step_fn(p, obs, k):
        # key advances INSIDE the jitted step: one dispatch per env step
        # instead of three (split + fold_in used to run as separate host
        # programs — measurable at A2C's rollout_steps=5 granularity)
        k_sample, k_next = jax.random.split(k)
        out, value = agent.apply(p, obs)
        actions, logprob, _ = sample_actions(out, actions_dim, is_continuous, k_sample, dist_type=dist_type)
        return actions, logprob, value[..., 0], k_next

    # compile-once routing: AOT-compiled per abstract signature, counted by
    # the recompile detector (parallel/compile.py)
    policy_step_fn = fabric.compile(
        policy_step_fn,
        name=f"{cfg.algo.name}.policy_step",
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    @jax.jit
    def values_fn(p, obs):
        _, value = agent.apply(p, obs)
        return value[..., 0]

    player_params = fabric.to_host(params)

    def train_phase(p, o_state, rollout, last_obs, traced_ent_coef=None):
        """GAE + one full-batch gradient step, in one device program.

        ``traced_ent_coef`` lets the population path pass the entropy
        coefficient as per-member traced data (hyperparameters-as-data,
        docs/population.md); ``None`` (the compiled single-agent signature)
        bakes in the static config value."""
        e_coef = ent_coef if traced_ent_coef is None else traced_ent_coef
        T, B = rollout["rewards"].shape
        flat_obs = {k: rollout[k].reshape((T * B,) + rollout[k].shape[2:]) for k in obs_keys}
        _, values0 = agent.apply(p, flat_obs)
        values0 = values0[..., 0].reshape(T, B)
        next_value = values_fn(p, last_obs)
        returns, advantages = gae(
            rollout["rewards"], values0, rollout["dones"], next_value, gamma, gae_lambda
        )

        def loss_fn(p):
            out, new_values = agent.apply(p, flat_obs)
            lp, ent = evaluate_actions(
                out, rollout["actions"].reshape(T * B, -1), actions_dim, is_continuous, dist_type=dist_type
            )
            pg = policy_loss(lp, advantages.reshape(-1), reduction)
            vl = value_loss(new_values[..., 0], returns.reshape(-1), reduction)
            e = ent.mean()
            return pg + vf_coef * vl - e_coef * e, (pg, vl, e)

        (loss, (pg, vl, e)), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, o_state = optimizer.update(grads, o_state, p)
        p = optax.apply_updates(p, updates)
        return p, o_state, (pg, vl, e)

    # rollout/last-obs staging is donated too (argnums 2/3): one dispatch
    # consumes the staged block exactly once (see ppo.py)
    train_phase_fn = train_phase  # raw callable: the Anakin path fuses it
    train_phase = fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1, 2, 3),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )
    guard_on = bool(cfg.buffer.get("transfer_guard", False))

    rollout_steps = int(cfg.algo.rollout_steps)
    sharded_envs, _ = fabric.env_sharding_plan(num_envs, "A2C")
    # buffer.share_data needs no branch here: this A2C takes ONE full-batch
    # gradient step over the global rollout, so the "shared global pool"
    # (share_data=True) and "per-rank batches + gradient all-reduce"
    # (share_data=False) semantics produce the same update by linearity
    # (reference: sheeprl/algos/a2c/a2c.py:41-54,371 minibatches instead)
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * rollout_steps * fabric.num_processes
    if use_population:
        # every member steps its own env shard: the population multiplies
        # the env steps per fused update, so total_steps buys fewer updates
        policy_steps_per_iter *= pop_size
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    base_lr = float(cfg.algo.optimizer.lr)

    # ---------------- Anakin fused rollout+train ----------------------------
    if use_anakin:
        from sheeprl_tpu.envs.jax.anakin import (
            init_actor_state,
            make_rollout_fn,
            traced_polynomial_decay,
        )

        def _sample(out, k):
            return sample_actions(out, actions_dim, is_continuous, k, dist_type=dist_type)

        rollout_fn = make_rollout_fn(
            venv,
            agent.apply,
            _sample,
            cnn_keys=cnn_keys,
            mlp_keys=mlp_keys,
            action_space=act_space,
            gamma=gamma,
            rollout_steps=rollout_steps,
            store_logprobs=False,  # A2C re-evaluates actions under current params
        )

        def anakin_phase(p, o_state, actor, k):
            """``lax.scan`` env rollout + GAE + the full-batch gradient step
            in ONE device program (lr annealing in-trace — see ppo.py)."""
            k_roll, k_next = jax.random.split(k)
            if cfg.algo.anneal_lr:
                o_state = set_learning_rate(
                    o_state,
                    traced_polynomial_decay(actor["update"], initial=base_lr, max_decay_steps=total_iters),
                )
            actor, rollout, last_obs, stats = rollout_fn(p, actor, k_roll)
            p, o_state, losses = train_phase_fn(p, o_state, rollout, last_obs)
            return p, o_state, actor, k_next, losses, stats

        if use_population:
            # ------------ population: vmap whole agents over P ------------
            from sheeprl_tpu import telemetry
            from sheeprl_tpu.population import (
                PBTConfig,
                PopulationMonitor,
                init_population_state,
                make_population_phase,
                tile_stack,
                write_population_summary,
            )

            pbt_cfg = PBTConfig.from_cfg(
                cfg, base={"lr": base_lr, "ent_coef": ent_coef}
            )

            def member_phase(p, o_state, actor, k, hp):
                """ONE member's fused rollout+train with its hyperparameters
                as traced data (A2C has no clip; lr rides the injected
                opt-state, ent_coef enters the loss)."""
                o_state = set_learning_rate(o_state, hp["lr"])
                actor, rollout, last_obs, stats = rollout_fn(p, actor, k)
                p, o_state, losses = train_phase_fn(p, o_state, rollout, last_obs, hp["ent_coef"])
                return p, o_state, actor, losses, stats

            population_step = fabric.compile(
                make_population_phase(member_phase, pbt_cfg),
                name=f"{cfg.algo.name}.population_phase",
                donate_argnums=(0, 1, 2, 3),
                max_recompiles=cfg.algo.get("max_recompiles"),
            )

            pop_resume = state.get("population") if state else None
            if state:
                params = fabric.replicate(jax.tree.map(jnp.asarray, state["agent"]))
                opt_state = fabric.replicate(state["opt_state"])
            else:
                params = jax.device_put(tile_stack(params, pop_size), fabric.replicated)
                opt_state = jax.device_put(jax.vmap(optimizer.init)(params), fabric.replicated)

            def _init_member(k):
                env_state, _ = venv.reset(k)
                return {
                    "env": env_state,
                    "ep_ret": jnp.zeros((num_envs,), jnp.float32),
                    "ep_len": jnp.zeros((num_envs,), jnp.int32),
                }

            members = jax.vmap(_init_member)(
                jax.random.split(jax.random.fold_in(key, fabric.global_rank + 1), pop_size)
            )
            members["update"] = jnp.full((pop_size,), start_iter - 1, jnp.int32)
            pop_state = init_population_state(members, pbt_cfg, num_envs)
            if pop_resume:
                pop_state["fitness"] = jnp.asarray(pop_resume["fitness"])
                pop_state["ep_count"] = jnp.asarray(pop_resume["ep_count"])
                pop_state["exploits"] = jnp.asarray(pop_resume["exploits"])
                hp_state = {name: jnp.asarray(v) for name, v in pop_resume["hp"].items()}
            else:
                hp_state = pbt_cfg.init_hyperparams(jax.random.fold_in(key, pop_size))
            pop_state = jax.device_put(pop_state, fabric.replicated)
            hp_state = jax.device_put(hp_state, fabric.replicated)
            pop_monitor = PopulationMonitor()
            telemetry.HUB.register("population", pop_monitor)
            anakin_step = None
            actor_state = None
        else:
            anakin_step = fabric.compile(
                anakin_phase,
                name=f"{cfg.algo.name}.anakin_phase",
                donate_argnums=(0, 1, 2),
                max_recompiles=cfg.algo.get("max_recompiles"),
            )
            actor_state = init_actor_state(
                fabric, venv, jax.random.fold_in(key, fabric.global_rank + 1), start_iter - 1, sharded_envs
            )
        rb = None
    else:
        rb = ReplayBuffer(
            rollout_steps,
            num_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
            obs_keys=obs_keys,
        )

    step_data: Dict[str, np.ndarray] = {}
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    if envs is not None:
        obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    last_losses = None
    # per-rank player key stream, advanced inside policy_step_fn; the main
    # `key` stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    for update in range(start_iter, total_iters + 1):
        if use_anakin:
            # -------- fused rollout+train: ONE dispatch per update ---------
            with timer("Time/train_time"):
                with steady_guard(guard_on and update > start_iter):
                    if use_population:
                        # the WHOLE population trains in this one dispatch
                        params, opt_state, pop_state, hp_state, key, last_losses, ep_stats = (
                            population_step(params, opt_state, pop_state, hp_state, key)
                        )
                    else:
                        params, opt_state, actor_state, key, last_losses, ep_stats = anakin_step(
                            params, opt_state, actor_state, key
                        )
                if use_population:
                    # per-member (P,) losses → scalars for the aggregator
                    last_losses = jax.tree.map(lambda x: x.mean(), last_losses)
                policy_step += policy_steps_per_iter
            if cfg.metric.log_level > 0:
                from sheeprl_tpu.envs.jax.anakin import episode_stats_from_device

                rets, lens = episode_stats_from_device(ep_stats)
                for ep_ret, ep_len in zip(rets, lens):
                    aggregator.update("Rewards/rew_avg", float(ep_ret))
                    aggregator.update("Game/ep_len_avg", int(ep_len))
                if use_population:
                    # Population/* hub family: tiny D2H pulls on the logging
                    # cadence (the guard is H2D-scoped)
                    pop_monitor.observe(
                        pop_state["fitness"], hp_state, pop_state["exploits"]
                    )
        else:
            with timer("Time/env_interaction_time"):
                with jax.default_device(host):
                    for _ in range(rollout_steps):
                        policy_step += num_envs * fabric.num_processes
                        dev_obs = prepare_obs(obs, cnn_keys, mlp_keys)
                        actions, logprobs, _, player_key = policy_step_fn(
                            player_params, dev_obs, player_key
                        )
                        actions_np = np.asarray(actions)
                        next_obs, rewards, terminated, truncated, info = envs.step(
                            actions_for_env(actions_np, act_space)
                        )
                        dones = np.logical_or(terminated, truncated)
                        rewards = np.asarray(rewards, np.float32)
                        if np.any(truncated):
                            final_obs = final_obs_rows(info, np.nonzero(truncated)[0], obs_keys)
                            if final_obs is not None:
                                padded = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
                                for k in obs_keys:
                                    padded[k][truncated] = final_obs[k]
                                vals = np.asarray(
                                    values_fn(player_params, prepare_obs(padded, cnn_keys, mlp_keys))
                                )
                                rewards[truncated] += gamma * vals[truncated]

                        for k in obs_keys:
                            step_data[k] = np.asarray(obs[k])[None]
                        step_data["actions"] = actions_np[None]
                        step_data["rewards"] = rewards[None]
                        step_data["dones"] = dones[None].astype(np.float32)
                        rb.add({k: v[..., None] if v.ndim == 2 else v for k, v in step_data.items()})

                        obs = next_obs
                        for ep_ret, ep_len in episode_stats(info):
                            aggregator.update("Rewards/rew_avg", ep_ret)
                            aggregator.update("Game/ep_len_avg", ep_len)

            with timer("Time/train_time"):
                # donated device staging: host-numpy normalization + EXPLICIT
                # device_puts (data/device_replay.stage_rollout), rollout donated
                # into the one-dispatch update (see ppo.py)
                local = rb.buffer
                host_rollout = {k: obs_to_np(local[k], k in cnn_keys, rollout=True) for k in obs_keys}
                host_rollout["actions"] = np.asarray(local["actions"])
                host_rollout["rewards"] = np.asarray(local["rewards"][..., 0])
                host_rollout["dones"] = np.asarray(local["dones"][..., 0])
                rollout = stage_rollout(fabric, host_rollout, axis=1, sharded=sharded_envs)
                host_last = {k: obs_to_np(np.asarray(obs[k]), k in cnn_keys) for k in obs_keys}
                last_obs_dev = stage_rollout(fabric, host_last, axis=0, sharded=sharded_envs)
                with steady_guard(guard_on and update > start_iter):
                    params, opt_state, last_losses = train_phase(params, opt_state, rollout, last_obs_dev)
                player_params = fabric.to_host(params)

        # (Anakin mode anneals lr in-trace from the donated update counter)
        if cfg.algo.anneal_lr and not use_anakin:
            new_lr = polynomial_decay(update, initial=base_lr, final=0.0, max_decay_steps=total_iters)
            opt_state = set_learning_rate(opt_state, new_lr)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                pg, vl, e = last_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", e)
            last_log = flush_metrics(aggregator, timer, logger, policy_step, last_log)

        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            if use_population:
                # params/opt_state above are already the stacked (P, ...)
                # pytrees; the PBT carry rides its own subtree
                ckpt_state["population"] = {
                    "fitness": pop_state["fitness"],
                    "ep_count": pop_state["ep_count"],
                    "exploits": pop_state["exploits"],
                    "hp": hp_state,
                }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    if envs is not None:
        envs.close()
    ckpt_mgr.finalize()
    if use_population and fabric.is_global_zero:
        # machine-readable member snapshot for the run_ci PBT drill and
        # bench --mode population
        write_population_summary(log_dir, pop_state, hp_state, policy_step)
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        if use_population:
            # eval the current BEST member (fitness argmax)
            best = int(np.asarray(pop_state["fitness"]).argmax())
            player_params = fabric.to_host(jax.tree.map(lambda x: x[best], params))
        elif use_anakin:
            # the fused path never refreshes the host player copy — pull
            # the final params once for the eval episode
            player_params = fabric.to_host(params)
        test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
