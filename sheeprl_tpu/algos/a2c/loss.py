"""A2C losses (reference: sheeprl/algos/a2c/loss.py)."""

from __future__ import annotations

import jax


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    return x


def policy_loss(logprobs: jax.Array, advantages: jax.Array, reduction: str = "sum") -> jax.Array:
    """Vanilla policy gradient: -E[logπ(a|s) · Â] (advantages stop-gradient)."""
    return _reduce(-logprobs * jax.lax.stop_gradient(advantages), reduction)


def value_loss(values: jax.Array, returns: jax.Array, reduction: str = "sum") -> jax.Array:
    # plain mse, matching the reference scale (the reference's A2C reuses the
    # PPO value_loss: sheeprl/algos/a2c/a2c.py:15 → ppo/loss.py:45-55)
    return _reduce((values - returns) ** 2, reduction)
