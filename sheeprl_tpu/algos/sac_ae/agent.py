"""SAC-AE agent (flax) — pixel SAC with an autoencoder
(reference: sheeprl/algos/sac_ae/agent.py:1-640).

Structure: a conv (+MLP) encoder produces a feature vector shared by actor
and critics; a decoder reconstructs observations for the autoencoder loss.
Gradient routing mirrors the reference: the CRITIC loss backpropagates into
the encoder, the ACTOR uses stop-gradient features, the decoder loss trains
encoder+decoder with an L2 latent penalty.  The target critic has an EMA
copy of both critic heads AND encoder (separate taus).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor, SACCriticEnsemble
from sheeprl_tpu.models.models import CNN, MLP, MultiDecoder


class AEEncoder(nn.Module):
    """Conv encoder (+ vector branch) → LayerNorm'd feature vector."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    features_dim: int = 64
    cnn_mult: int = 16
    dense_units: int = 64
    mlp_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-1)
            x = CNN(
                channels=(self.cnn_mult, self.cnn_mult * 2, self.cnn_mult * 4),
                kernel_sizes=4,
                strides=2,
                activation="relu",
                dtype=self.dtype,
                name="cnn",
            )(x)
            feats.append(x)
        if self.mlp_keys:
            v = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(
                MLP(
                    hidden_sizes=(self.dense_units,) * self.mlp_layers,
                    activation="relu",
                    dtype=self.dtype,
                    name="mlp",
                )(v)
            )
        x = jnp.concatenate(feats, axis=-1)
        x = nn.Dense(self.features_dim, dtype=jnp.float32, name="proj")(x)
        x = nn.LayerNorm(name="ln")(x)
        return jnp.tanh(x)


def build_agent(
    fabric: Any,
    act_dim: int,
    cfg: Any,
    obs_space: Any,
    state: Optional[Dict[str, Any]] = None,
):
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_shapes = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            shape = (shape[1], shape[2], shape[0] * shape[3])
        cnn_shapes[k] = tuple(shape)
    mlp_shapes = {k: int(np.prod(obs_space[k].shape)) for k in mlp_keys}
    dtype = fabric.precision.compute_dtype

    encoder = AEEncoder(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        features_dim=cfg.algo.encoder.features_dim,
        cnn_mult=cfg.algo.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.encoder.dense_units,
        mlp_layers=cfg.algo.encoder.mlp_layers,
        dtype=dtype,
    )
    dec_mult = cfg.algo.decoder.cnn_channels_multiplier
    decoder = MultiDecoder(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_shapes=cnn_shapes,
        mlp_shapes=mlp_shapes,
        cnn_channels=(dec_mult * 2, dec_mult),
        cnn_stem_channels=dec_mult * 4,
        mlp_sizes=(cfg.algo.decoder.dense_units,) * cfg.algo.decoder.mlp_layers,
        activation="relu",
        dtype=dtype,
    )
    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.hidden_size, dtype=dtype)
    critic = SACCriticEnsemble(
        n_critics=cfg.algo.critic.n, hidden_size=cfg.algo.hidden_size, dtype=dtype
    )

    if state is not None:
        params = state
    else:
        key = jax.random.PRNGKey(cfg.seed)
        k_e, k_d, k_a, k_c = jax.random.split(key, 4)
        dummy_obs = {}
        for k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), jnp.float32)
        for k in mlp_keys:
            dummy_obs[k] = jnp.zeros((1, mlp_shapes[k]), jnp.float32)
        enc_params = encoder.init(k_e, dummy_obs)
        feats = encoder.apply(enc_params, dummy_obs)
        dec_params = decoder.init(k_d, feats)
        actor_params = actor.init(k_a, feats)
        critic_params = critic.init(k_c, feats, jnp.zeros((1, act_dim), jnp.float32))
        params = {
            "encoder": enc_params,
            "decoder": dec_params,
            "actor": actor_params,
            "critic": critic_params,
            "target_encoder": jax.tree.map(jnp.copy, enc_params),
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "log_alpha": jnp.asarray(np.log(cfg.algo.alpha.alpha), jnp.float32),
        }
    return encoder, decoder, actor, critic, fabric.replicate(params)
