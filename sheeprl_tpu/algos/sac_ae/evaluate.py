"""SAC-AE evaluation entrypoint (reference: sheeprl/algos/sac_ae/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="sac_ae")
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    env = make_env(cfg, cfg.seed, 0)()
    act_dim = int(np.prod(env.action_space.shape))
    obs_space = env.observation_space
    env.close()
    encoder, decoder, actor, critic, params = build_agent(fabric, act_dim, cfg, obs_space, state["agent"])
    host = fabric.to_host({"encoder": params["encoder"], "actor": params["actor"]})
    test(encoder, actor, host, cfg, log_dir, logger)
