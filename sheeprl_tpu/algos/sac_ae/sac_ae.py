"""SAC-AE — pixel SAC with autoencoder
(reference: sheeprl/algos/sac_ae/sac_ae.py:119-502).

Gradient routing parity: critic loss trains critic AND encoder; actor
trains on stop-gradient features (at its own update frequency); the decoder
loss (MSE reconstruction + L2 latent penalty) trains encoder+decoder at its
own frequency; target critic/encoder EMA with separate taus.  The reference
needs ``DDPStrategy(find_unused_parameters=True)`` for this dance
(reference: cli.py:108-116) — the functional JAX formulation has no unused-
parameter problem: each loss differentiates exactly the param groups it
names, update cadences are ``lax.cond`` branches inside the scanned update.

Same TPU structure as SAC: host player, bulk-sampled update blocks, one
jitted dispatch per ratio window.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.agent import ema_update, sample_action
from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss, critic_loss
from sheeprl_tpu.algos.dreamer_v3.utils import normalize_obs_block
from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import (
    DeviceReplay,
    HostSpill,
    estimate_step_bytes,
    fit_hbm_window,
    fused_uniform_train,
    resolve_device_replay,
    steady_guard,
    update_chunks,
)
from sheeprl_tpu.parallel.fabric import PlayerSync
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    Ratio,
    TrainWindow,
    merge_framestack,
    save_configs,
    window_scan,
)


def _prep(obs: Dict[str, np.ndarray], cnn_keys, mlp_keys) -> Dict[str, jax.Array]:
    out = {}
    for k in cnn_keys:
        x = np.asarray(obs[k])
        if x.ndim == 5:
            x = merge_framestack(x)
        out[k] = jnp.asarray(x, jnp.float32) / 255.0
    for k in mlp_keys:
        out[k] = jnp.asarray(np.asarray(obs[k], np.float32).reshape(np.asarray(obs[k]).shape[0], -1))
    return out


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = vectorize(
        cfg,
        [
            make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
            for i in range(num_envs)
        ],
    )
    act_space = envs.single_action_space
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC-AE supports continuous (Box) action spaces only, like the reference")
    obs_space = envs.single_observation_space
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    act_dim = int(np.prod(act_space.shape))
    act_low = np.asarray(act_space.low, np.float32)
    act_high = np.asarray(act_space.high, np.float32)

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    encoder, decoder, actor, critic, params = build_agent(
        fabric, act_dim, cfg, obs_space, state.get("agent")
    )

    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)
    encoder_opt = build_optimizer(cfg.algo.encoder.optimizer)
    decoder_opt = build_optimizer(cfg.algo.decoder.optimizer)
    opt_state = fabric.replicate(
        state.get("opt_state")
        or {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
            "encoder": encoder_opt.init(params["encoder"]),
            "decoder": decoder_opt.init(params["decoder"]),
        }
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    psync = PlayerSync(
        fabric, cfg, extract=lambda p: {"encoder": p["encoder"], "actor": p["actor"]}
    )
    host = psync.device  # single resolution of algo.player.device
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    encoder_tau = float(cfg.algo.encoder.tau)
    target_entropy = -float(act_dim)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    actor_freq = int(cfg.algo.actor.per_rank_update_freq)
    decoder_freq = int(cfg.algo.decoder.per_rank_update_freq)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)

    def to_env_actions(a: np.ndarray) -> np.ndarray:
        return act_low + (a + 1.0) * 0.5 * (act_high - act_low)

    def act_fn(p, obs, k, greedy=False):
        # key advances INSIDE the jitted step (one host dispatch per env step)
        k_sample, k_next = jax.random.split(k)
        feats = encoder.apply(p["encoder"], obs)
        a, _ = sample_action(actor, p["actor"], feats, k_sample, greedy=greedy)
        return a, k_next

    # compile-once routing: AOT-compiled per abstract signature, counted by
    # the recompile detector
    act_fn = fabric.compile(
        act_fn,
        name=f"{cfg.algo.name}.act_fn",
        static_argnames=("greedy",),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    player_params = psync.init(params)

    # ---------------- one scanned update -------------------------------------
    def one_update(carry, batch_and_key):
        p, o_state, step_idx = carry
        batch, k = batch_and_key
        k_next, k_pi, k_dec = jax.random.split(k, 3)
        alpha = jnp.exp(p["log_alpha"])

        obs = normalize_obs_block(batch, cnn_keys, obs_keys, offset=0.0)
        next_obs = normalize_obs_block(
            {kk: batch[f"next_{kk}"] for kk in obs_keys}, cnn_keys, obs_keys, offset=0.0
        )

        # -- critic (trains critic AND encoder)
        next_feats = encoder.apply(p["target_encoder"], next_obs)
        next_a, next_lp = sample_action(actor, p["actor"], next_feats, k_next)
        target_qs = critic.apply(p["target_critic"], next_feats, next_a)
        target_v = jnp.min(target_qs, axis=0) - alpha * next_lp
        y = batch["rewards"] + gamma * (1.0 - batch["terminated"]) * target_v

        def c_loss(cp, ep):
            feats = encoder.apply(ep, obs)
            qs = critic.apply(cp, feats, batch["actions"])
            return critic_loss(qs, jax.lax.stop_gradient(y))

        vl, (c_grads, e_grads) = jax.value_and_grad(c_loss, argnums=(0, 1))(
            p["critic"], p["encoder"]
        )
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state["critic"], p["critic"])
        e_updates, new_e_opt = encoder_opt.update(e_grads, o_state["encoder"], p["encoder"])
        p = {
            **p,
            "critic": optax.apply_updates(p["critic"], c_updates),
            "encoder": optax.apply_updates(p["encoder"], e_updates),
        }
        o_state = {**o_state, "critic": new_c_opt, "encoder": new_e_opt}

        # -- actor + temperature (every actor_freq updates, on sg features)
        def do_actor(operand):
            p, o_state = operand
            feats = jax.lax.stop_gradient(encoder.apply(p["encoder"], obs))

            def a_loss(ap):
                a, lp = sample_action(actor, ap, feats, k_pi)
                qs = critic.apply(p["critic"], feats, a)
                return actor_loss(alpha, lp, jnp.min(qs, axis=0)), lp

            (pl, lp), a_grads = jax.value_and_grad(a_loss, has_aux=True)(p["actor"])
            a_updates, new_a_opt = actor_opt.update(a_grads, o_state["actor"], p["actor"])
            al, t_grads = jax.value_and_grad(lambda la: alpha_loss(la, lp, target_entropy))(
                p["log_alpha"]
            )
            t_updates, new_t_opt = alpha_opt.update(t_grads, o_state["alpha"], p["log_alpha"])
            p = {
                **p,
                "actor": optax.apply_updates(p["actor"], a_updates),
                "log_alpha": p["log_alpha"] + t_updates,
            }
            return (p, {**o_state, "actor": new_a_opt, "alpha": new_t_opt}), (pl, al)

        def skip_actor(operand):
            return operand, (jnp.zeros(()), jnp.zeros(()))

        (p, o_state), (pl, al) = jax.lax.cond(
            step_idx % actor_freq == 0, do_actor, skip_actor, (p, o_state)
        )

        # -- autoencoder (every decoder_freq updates)
        def do_decoder(operand):
            p, o_state = operand

            def d_loss(ep, dp):
                feats = encoder.apply(ep, obs)
                recon = decoder.apply(dp, feats)
                # reference decoder objective (sheeprl/algos/sac_ae/sac_ae.py:100-109):
                # per decoder key, mse against the 5-bit-quantized + dithered
                # target (cnn; utils.py:68-76) PLUS 0.5*lambda*||h||^2 — the L2
                # penalty is counted once per key, matching the reference loop
                l2 = 0.5 * l2_lambda * jnp.mean(jnp.sum(feats**2, axis=-1))
                loss = 0.0
                for i, kk in enumerate(obs_keys):
                    if kk in cnn_keys:
                        # obs normalized to [0,1] upstream; round back to the
                        # exact uint8 grid before the 5-bit floor — the fp32
                        # /255 round-trip can land one bucket low at exact
                        # multiples of 8 (ADVICE r4)
                        raw = jnp.round(obs[kk] * 255.0)
                        quant = jnp.floor(raw / 8.0) / 32.0
                        dither = jax.random.uniform(jax.random.fold_in(k_dec, i), obs[kk].shape) / 32.0
                        target = quant + dither - 0.5
                    else:
                        target = obs[kk]
                    loss = loss + jnp.mean((recon[kk] - target) ** 2) + l2
                return loss

            dl, (e_grads, d_grads) = jax.value_and_grad(d_loss, argnums=(0, 1))(
                p["encoder"], p["decoder"]
            )
            e_updates, new_e_opt = encoder_opt.update(e_grads, o_state["encoder"], p["encoder"])
            d_updates, new_d_opt = decoder_opt.update(d_grads, o_state["decoder"], p["decoder"])
            p = {
                **p,
                "encoder": optax.apply_updates(p["encoder"], e_updates),
                "decoder": optax.apply_updates(p["decoder"], d_updates),
            }
            return (p, {**o_state, "encoder": new_e_opt, "decoder": new_d_opt}), dl

        def skip_decoder(operand):
            return operand, jnp.zeros(())

        (p, o_state), dl = jax.lax.cond(
            step_idx % decoder_freq == 0, do_decoder, skip_decoder, (p, o_state)
        )

        # -- EMA targets
        do_ema = (step_idx % target_freq) == 0
        new_tc = ema_update(p["target_critic"], p["critic"], tau)
        new_te = ema_update(p["target_encoder"], p["encoder"], encoder_tau)
        p = {
            **p,
            "target_critic": jax.tree.map(lambda n, o: jnp.where(do_ema, n, o), new_tc, p["target_critic"]),
            "target_encoder": jax.tree.map(lambda n, o: jnp.where(do_ema, n, o), new_te, p["target_encoder"]),
        }
        return (p, o_state, step_idx + 1), (vl, pl, al, dl)

    def train_phase(p, o_state, batches, k, step0):
        U = batches["rewards"].shape[0]
        keys = jax.random.split(k, U)
        (p, o_state, _), losses = window_scan(
            one_update, (p, o_state, step0), (batches, keys), unroll=bool(cnn_keys)
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), losses)

    train_phase = fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    # ---------------- counters / buffer --------------------------------------
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    learning_starts = int(cfg.algo.learning_starts) // policy_steps_per_iter if not cfg.dry_run else 0
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    if state:
        learning_starts += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])
    window = TrainWindow(
        cfg.algo.get("train_window_iters", 1),
        pending=int(state.get("pending_gradient_steps", 0)) if state else 0,
    )
    if state and "psync" in state:
        psync.load_state_dict(state["psync"])

    # device-resident replay (data/device_replay.py): the whole ring — pixel
    # obs AND their stored next_<k> rows — lives in HBM sharded over the mesh
    # `data` axis, sampling compiled into the update dispatch (supersedes the
    # retired pixel-only DeviceMirror and the window_chunks byte probe)
    capacity = int(cfg.buffer.size) // num_envs
    memmap_dir = os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None
    use_device_replay = resolve_device_replay(cfg, fabric.accelerator)
    if use_device_replay:
        # next_<k> copies double the obs bytes; actions/reward/flag row tail
        step_bytes = estimate_step_bytes(
            obs_space, obs_keys, extra_bytes=4 * (act_dim + 2), copies_per_key=2
        )
        hbm_window, spill_needed = fit_hbm_window(
            capacity, num_envs, step_bytes, cfg.buffer.get("hbm_window")
        )
        spill = (
            HostSpill(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
            if spill_needed
            else None
        )
        rb: Any = DeviceReplay(
            hbm_window, num_envs, mesh=fabric.mesh, data_axis=fabric.data_axis, spill=spill
        )
    else:
        rb = ReplayBuffer(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    batch_size = int(cfg.algo.per_rank_batch_size) * fabric.local_world_size

    train_phase_dev = None
    if use_device_replay:
        def _prep_batch(b):
            out: Dict[str, jax.Array] = {
                "actions": b["actions"],
                "rewards": b["rewards"][..., 0],
                "terminated": b["terminated"][..., 0],
            }
            for k in cnn_keys:
                for src in (k, f"next_{k}"):
                    x = b[src]
                    if x.ndim >= 6:  # (U, B, S, H, W, C) framestack
                        x = merge_framestack(x, jnp)
                    out[src] = x  # uint8; /255 on device in the update body
            for k in mlp_keys:
                for src in (k, f"next_{k}"):
                    x = b[src].astype(jnp.float32)
                    out[src] = x.reshape(*x.shape[:2], -1)
            return out

        train_phase_dev = fused_uniform_train(
            fabric,
            train_phase,
            rb,
            batch_size,
            _prep_batch,
            name=f"{cfg.algo.name}.train_phase_device",
            max_recompiles=cfg.algo.get("max_recompiles"),
        )
    guard_on = bool(cfg.buffer.get("transfer_guard", False)) and use_device_replay

    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    last_losses = None
    counter_dev = None  # device-resident grad-step counter (zero-copy path)
    train_windows = 0  # completed dispatched windows (guards arm past warmup)
    # per-rank player key stream, advanced inside act_fn; the main `key`
    # stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    for update in range(start_iter, total_iters + 1):
        policy_step += num_envs * fabric.num_processes
        with timer("Time/env_interaction_time"):
            if update <= learning_starts and not state:
                env_actions = np.stack([act_space.sample() for _ in range(num_envs)])
                span = act_high - act_low
                actions = np.clip(2.0 * (env_actions - act_low) / np.where(span == 0, 1, span) - 1.0, -1, 1)
            else:
                with jax.default_device(host):
                    a, player_key = act_fn(player_params, _prep(obs, cnn_keys, mlp_keys), player_key)
                    actions = np.asarray(a)
                env_actions = to_env_actions(actions)
            next_obs, rewards, terminated, truncated, info = envs.step(env_actions)
            dones = np.logical_or(terminated, truncated)

            real_next = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            done_idx = np.nonzero(dones)[0]
            if done_idx.size:
                final = final_obs_rows(info, done_idx, obs_keys)
                if final is not None:
                    for k in obs_keys:
                        real_next[k][done_idx] = final[k]

            step = {
                "actions": actions[None].astype(np.float32),
                "rewards": np.asarray(rewards, np.float32)[None, :, None],
                "terminated": terminated.astype(np.float32)[None, :, None],
            }
            for k in obs_keys:
                step[k] = np.asarray(obs[k])[None]
                step[f"next_{k}"] = real_next[k][None]
            rb.add(step)
            obs = next_obs
            for ep_ret, ep_len in episode_stats(info):
                aggregator.update("Rewards/rew_avg", ep_ret)
                aggregator.update("Game/ep_len_avg", ep_len)

        if update >= learning_starts:
            # windowed multi-iteration dispatch, same contract as sac.py
            # (algo.train_window_iters; update math/count unchanged)
            per_rank_gradient_steps = window.push(
                ratio(policy_step / fabric.world_size), update, learning_starts, total_iters
            )
            if per_rank_gradient_steps > 0 and train_phase_dev is not None:
                with timer("Time/train_time"):
                    # zero-copy steady state: sampling + gather compiled into
                    # the update dispatch, counter rides as device data, the
                    # transfer guard (optional) proves no implicit H2D past
                    # the first window; power-of-two chunks reuse executables
                    if counter_dev is None:
                        # replicated on the mesh, matching the program's output
                        # placement — a single-device stage would cost one
                        # extra (first-window) executable on multi-device
                        counter_dev = fabric.replicate(np.int32(grad_step_counter))
                    player_params = psync.before_dispatch(player_params)
                    with steady_guard(guard_on and train_windows > 0):
                        for u in update_chunks(
                            per_rank_gradient_steps,
                            bytes_per_update=rb.sampled_bytes_per_update(batch_size),
                        ):
                            key, tk = jax.random.split(key)
                            params, opt_state, counter_dev, last_losses = train_phase_dev(
                                params, opt_state, rb.buffers, rb.cursor, tk,
                                counter_dev, n_samples=u,
                            )
                            grad_step_counter += u
                    train_windows += 1
                    player_params = psync.after_dispatch(params, player_params)
            elif per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    # host-numpy fallback: burst windows chunked into powers
                    # of two for compile reuse; one player sync per ratio
                    # window, not per chunk (a per-chunk refresh pulls full
                    # player params D2H each time — see the dreamer loop)
                    player_params = psync.before_dispatch(player_params)
                    for u in update_chunks(per_rank_gradient_steps):
                        sample = rb.sample(batch_size, n_samples=u)
                        batches: Dict[str, jax.Array] = {
                            "actions": jnp.asarray(sample["actions"]),
                            "rewards": jnp.asarray(sample["rewards"][..., 0]),
                            "terminated": jnp.asarray(sample["terminated"][..., 0]),
                        }
                        for k in cnn_keys:
                            for src in (k, f"next_{k}"):
                                x = np.asarray(sample[src])
                                # framestacked sample is (U, B, S, H, W, C) =
                                # 6-dim — merge stacks into channels before
                                # the encoder
                                if x.ndim >= 6:
                                    x = merge_framestack(x)
                                batches[src] = jnp.asarray(x)  # uint8; /255 on device
                        for k in mlp_keys:
                            for src in (k, f"next_{k}"):
                                x = np.asarray(sample[src], np.float32)
                                batches[src] = jnp.asarray(x.reshape(*x.shape[:2], -1))
                        batches = fabric.shard_batch(batches, axis=1)
                        key, tk = jax.random.split(key)
                        params, opt_state, last_losses = train_phase(
                            params, opt_state, batches, tk, jnp.int32(grad_step_counter)
                        )
                        grad_step_counter += u
                    player_params = psync.after_dispatch(params, player_params)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                vl, pl, al, dl = last_losses
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/policy_loss", pl)
                aggregator.update("Loss/alpha_loss", al)
                aggregator.update("Loss/reconstruction_loss", dl)
            last_log = flush_metrics(
                aggregator, timer, logger, policy_step, last_log,
                extra_metrics=psync.metrics(),  # deferred-sync staleness (ISSUE 12)
            )

        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "psync": psync.state_dict(),
                "grad_steps": grad_step_counter,
                "pending_gradient_steps": window.pending,
            }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    envs.close()
    if getattr(rb, "spill", None) is not None:
        rb.spill.close()
    ckpt_mgr.finalize()
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        from sheeprl_tpu.algos.sac_ae.utils import test

        # the deferred-sync player may be one window stale: sync once more
        player_params = psync.init(params)
        test(encoder, actor, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
