"""SAC-AE support utilities (reference: sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
MODELS_TO_REGISTER = {"encoder", "decoder", "agent"}


def test(encoder: Any, actor: Any, params: Any, cfg: Any, log_dir: str, logger: Any = None, greedy: bool = True) -> float:
    import jax.numpy as jnp

    from sheeprl_tpu.algos.sac.agent import sample_action
    from sheeprl_tpu.algos.sac_ae.sac_ae import _prep
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)

    @jax.jit
    def act(p, o, k):
        feats = encoder.apply(p["encoder"], o)
        a, _ = sample_action(actor, p["actor"], feats, k, greedy=greedy)
        return a

    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    low = np.asarray(env.action_space.low, np.float32)
    high = np.asarray(env.action_space.high, np.float32)
    done, cum_reward = False, 0.0
    while not done:
        batched = {k: np.asarray(v)[None] for k, v in obs.items()}
        key, sk = jax.random.split(key)
        action = np.asarray(act(params, _prep(batched, cnn_keys, mlp_keys), sk))[0]
        scaled = low + (action + 1.0) * 0.5 * (high - low)
        obs, reward, terminated, truncated, _ = env.step(scaled)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward
