"""DreamerV2 — discrete-latent world-model RL
(reference: sheeprl/algos/dreamer_v2/dreamer_v2.py:1-792, agent.py:1-1104,
loss.py:1-85).

Shares the RSSM/encoder/decoder/actor module family with the DreamerV3
implementation (the reference shares them the same way), configured for V2:
ELU activations without LayerNorm stages, no unimix, no symlog inputs,
Gaussian (unit-variance) observation/reward heads, α-balanced KL
(kl_balancing_alpha=0.8, free-avg), a HARD-copied target value network, and
a mixed REINFORCE/dynamics-backprop actor objective (``objective_mix``).

TPU structure identical to DreamerV3: scanned RSSM, scanned imagination,
one jitted dispatch per ratio window, host latent player.  Replay uses the
sequential per-env buffer; ``buffer.type=episode`` selects the EpisodeBuffer
with end-prioritized sampling (reference supports both for V2).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import Actor, Critic, WorldModel
from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values, normalize_obs_block
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.p2e_utils import ensemble_disagreement
from sheeprl_tpu.utils.distribution import Bernoulli, Normal, OneHotCategorical
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import window_scan


def build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state=None):
    """DV3 module family with V2 settings (see module docstring)."""
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    wm_cfg = cfg.algo.world_model
    cnn_shapes = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            shape = (shape[1], shape[2], shape[0] * shape[3])
        cnn_shapes[k] = tuple(shape)
    mlp_shapes = {k: int(np.prod(obs_space[k].shape)) for k in mlp_keys}
    dtype = fabric.precision.compute_dtype

    world_model = WorldModel(
        cnn_keys=cnn_keys, mlp_keys=mlp_keys, cnn_shapes=cnn_shapes, mlp_shapes=mlp_shapes,
        actions_dim=tuple(actions_dim),
        cnn_mult=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        recurrent_size=wm_cfg.recurrent_model.recurrent_state_size,
        hidden_size=wm_cfg.transition_model.hidden_size,
        repr_hidden_size=wm_cfg.representation_model.hidden_size,
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        unimix=0.0,
        bins=1,                      # Gaussian reward head
        act=cfg.algo.dense_act,
        layer_norm=bool(cfg.algo.layer_norm),
        symlog_inputs=False,
        learnable_initial_state=False,
        dtype=dtype,
    )
    actor = Actor(
        actions_dim=tuple(actions_dim), is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units, mlp_layers=cfg.algo.actor.mlp_layers,
        act=cfg.algo.dense_act, layer_norm=bool(cfg.algo.layer_norm), unimix=0.0,
        min_std=cfg.algo.actor.min_std, max_std=1.0,
        init_std=cfg.algo.actor.init_std, action_clip=1.0, dtype=dtype,
    )
    critic = Critic(
        dense_units=cfg.algo.critic.dense_units, mlp_layers=cfg.algo.critic.mlp_layers,
        act=cfg.algo.dense_act, layer_norm=bool(cfg.algo.layer_norm), bins=1, dtype=dtype,
    )
    if state is not None:
        return world_model, actor, critic, fabric.replicate(state)

    key = jax.random.PRNGKey(cfg.seed)
    k_wm, k_actor, k_critic, k_s = jax.random.split(key, 4)
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, mlp_shapes[k]), jnp.float32)
    stoch = wm_cfg.stochastic_size * wm_cfg.discrete_size
    rec = wm_cfg.recurrent_model.recurrent_state_size
    wm_params = world_model.init(
        k_wm, dummy_obs, jnp.zeros((1, rec)), jnp.zeros((1, stoch)),
        jnp.zeros((1, int(sum(actions_dim)))), jnp.ones((1, 1)), k_s,
    )
    latent = jnp.zeros((1, stoch + rec))
    params = {
        "world_model": wm_params,
        "actor": actor.init(k_actor, latent),
        "critic": (cp := critic.init(k_critic, latent)),
        "target_critic": jax.tree.map(jnp.copy, cp),
    }
    return world_model, actor, critic, fabric.replicate(params)


def make_train_phase(fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
                     cnn_keys, mlp_keys, is_continuous, p2e=None, params=None, opt_state=None):
    # ``p2e``: optional Plan2Explore hook {ens_module, ens_opt, n, multiplier}
    # — trains the forward-model ensembles alongside the world model and runs
    # TWO behavior updates per step: the exploration actor with its own
    # critic + hard-copied target on the pure ensemble-disagreement intrinsic
    # reward, and the task actor/critic on extrinsic rewards (reference:
    # sheeprl/algos/p2e_dv2/p2e_dv2_exploration.py:236-431).
    obs_keys = tuple(cnn_keys) + tuple(mlp_keys)
    stoch_flat = world_model.stoch_flat
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    target_freq = int(cfg.algo.critic.target_network_update_freq)
    ent_coef = float(cfg.algo.actor.ent_coef)
    objective_mix = float(cfg.algo.actor.objective_mix)
    kl_alpha = float(cfg.algo.world_model.kl_balancing_alpha)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    use_continues = bool(cfg.algo.world_model.use_continues)
    discount_scale = float(cfg.algo.world_model.discount_scale_factor)

    remat = bool(cfg.algo.get("remat", False))

    def maybe_remat(f):
        return jax.checkpoint(f) if remat else f

    def wm_forward(wm_params, data, k):
        L, B = data["rewards"].shape
        obs = normalize_obs_block(data, cnn_keys, obs_keys)
        flat_obs = {kk: v.reshape((L * B,) + v.shape[2:]) for kk, v in obs.items()}
        embed = world_model.apply(wm_params, flat_obs, method=WorldModel.encode).reshape(L, B, -1)
        actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)
        is_first = data["is_first"].at[0].set(1.0)[..., None]
        h0 = jnp.zeros((B, rec_size))
        z0 = jnp.zeros((B, stoch_flat))

        def step(carry, xs):
            h, z = carry
            embed_t, act_t, first_t, k_t = xs
            h, z, post_logits, prior_logits = world_model.apply(
                wm_params, h, z, act_t, embed_t, first_t, k_t, method=WorldModel.dynamic
            )
            return (h, z), (h, z, post_logits, prior_logits)

        keys = jax.random.split(k, L)
        _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(
            maybe_remat(step), (h0, z0), (embed, actions, is_first, keys)
        )
        latents = jnp.concatenate([zs, hs], -1)
        flat_latents = latents.reshape(L * B, -1)

        recon = world_model.apply(wm_params, flat_latents, method=WorldModel.decode)
        obs_loss = 0.0
        for kk in cnn_keys:
            dist = Normal(recon[kk].reshape(obs[kk].shape), 1.0, event_dims=3)
            obs_loss = obs_loss - dist.log_prob(obs[kk])
        for kk in mlp_keys:
            dist = Normal(recon[kk].reshape(L, B, -1), 1.0, event_dims=1)
            obs_loss = obs_loss - dist.log_prob(obs[kk])

        reward_mean = world_model.apply(wm_params, flat_latents, method=WorldModel.reward_logits)
        pr = Normal(reward_mean.reshape(L, B), 1.0)
        reward_loss = -pr.log_prob(data["rewards"])

        if use_continues:
            cont_logits = world_model.apply(wm_params, flat_latents, method=WorldModel.continue_logits)
            pc = Bernoulli(cont_logits.reshape(L, B))
            continue_loss = -discount_scale * pc.log_prob((1.0 - data["terminated"]) * gamma)
        else:
            continue_loss = None

        total, aux = reconstruction_loss(
            obs_loss, reward_loss, continue_loss, post_logits, prior_logits,
            kl_balancing_alpha=kl_alpha, kl_free_nats=kl_free_nats, kl_regularizer=kl_regularizer,
        )
        aux["latents"] = latents
        aux["post_logits"] = post_logits
        aux["prior_logits"] = prior_logits
        return total, aux

    def behavior_update(p, o_state, latents, terminated, k, actor_key="actor",
                        critic_key="critic", target_key="target_critic",
                        reward_kind="extrinsic"):
        L, B = terminated.shape
        n = L * B
        start_latents = jax.lax.stop_gradient(latents.reshape(n, -1))

        def actor_loss_fn(actor_params):
            def img_step(carry, k_t):
                h, z = carry
                latent = jnp.concatenate([z, h], -1)
                k_a, k_z = jax.random.split(k_t)
                head = actor.apply(actor_params, jax.lax.stop_gradient(latent))
                action = actor.sample(head, k_a)
                h, z = world_model.apply(
                    p["world_model"], h, z, action, k_z, method=WorldModel.imagination
                )
                return (h, z), (latent, action)

            h0 = start_latents[:, stoch_flat:]
            z0 = start_latents[:, :stoch_flat]
            keys = jax.random.split(k, horizon + 1)
            _, (traj, actions_seq) = jax.lax.scan(maybe_remat(img_step), (h0, z0), keys)
            flat_traj = traj.reshape((horizon + 1) * n, -1)
            if reward_kind == "intrinsic":
                # ensemble disagreement over next-state predictions
                preds = p2e["ens_module"].apply(
                    p["ensembles"],
                    jax.lax.stop_gradient(
                        jnp.concatenate([traj, actions_seq], -1)
                    ).reshape((horizon + 1) * n, -1),
                )
                rewards = ensemble_disagreement(
                    preds.reshape(p2e["n"], horizon + 1, n, -1), p2e["multiplier"]
                )
            else:
                rewards = world_model.apply(
                    p["world_model"], flat_traj, method=WorldModel.reward_logits
                ).reshape(horizon + 1, n)
            values_t = critic.apply(p[target_key], flat_traj).reshape(horizon + 1, n)
            if use_continues:
                continues = Bernoulli(
                    world_model.apply(p["world_model"], flat_traj, method=WorldModel.continue_logits)
                    .reshape(horizon + 1, n)
                ).mean / gamma  # head predicts γ·(1-done); back to (1-done)
            else:
                continues = jnp.ones((horizon + 1, n))
            true_continue = (1.0 - terminated).reshape(1, n)
            continues = jnp.concatenate([true_continue, continues[1:]], 0)

            lambda_values = compute_lambda_values(
                rewards[1:], values_t[1:], continues[1:] * gamma, lmbda
            )
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, 0) / gamma)

            baseline = values_t[:-1]
            advantage = jax.lax.stop_gradient(lambda_values - baseline)
            heads = actor.apply(actor_params, jax.lax.stop_gradient(traj))
            lp = actor.log_prob(heads[:-1], jax.lax.stop_gradient(actions_seq[:-1]))
            reinforce = lp * advantage
            dynamics = lambda_values
            objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
            entropy = actor.entropy(heads[:-1])
            policy_loss = -jnp.mean(discount[:-1] * (objective + ent_coef * entropy))
            return policy_loss, (traj, lambda_values, discount)

        (pl, (traj, lambda_values, discount)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(p[actor_key])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state[actor_key], p[actor_key])
        p = {**p, actor_key: optax.apply_updates(p[actor_key], a_updates)}

        traj_sg = jax.lax.stop_gradient(traj[:-1])
        flat_sg = traj_sg.reshape(horizon * traj_sg.shape[1], -1)

        def critic_loss_fn(critic_params):
            qv = Normal(critic.apply(critic_params, flat_sg).reshape(horizon, -1), 1.0)
            return -jnp.mean(qv.log_prob(jax.lax.stop_gradient(lambda_values)) * discount[:-1])

        vl, c_grads = jax.value_and_grad(critic_loss_fn)(p[critic_key])
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state[critic_key], p[critic_key])
        p = {**p, critic_key: optax.apply_updates(p[critic_key], c_updates)}
        return p, {**o_state, actor_key: new_a_opt, critic_key: new_c_opt}, pl, vl

    def single_update(carry, inputs):
        p, o_state, counter = carry
        data, k = inputs
        k_wm, k_beh, k_task = jax.random.split(k, 3)
        (wm_l, aux), wm_grads = jax.value_and_grad(wm_forward, has_aux=True)(
            p["world_model"], data, k_wm
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, o_state["world_model"], p["world_model"])
        p = {**p, "world_model": optax.apply_updates(p["world_model"], wm_updates)}
        o_state = {**o_state, "world_model": new_wm_opt}
        if p2e is not None:
            L, B = data["rewards"].shape
            latents = aux["latents"]

            def ens_loss(ep):
                inp = jax.lax.stop_gradient(
                    jnp.concatenate([latents, data["actions"]], -1)
                )[:-1].reshape((L - 1) * B, -1)
                preds = p2e["ens_module"].apply(ep, inp)
                target = jax.lax.stop_gradient(latents[1:, :, : world_model.stoch_flat])
                return jnp.mean(
                    (preds.reshape(p2e["n"], L - 1, B, -1) - target[None]) ** 2
                )

            el, e_grads = jax.value_and_grad(ens_loss)(p["ensembles"])
            e_updates, new_e_opt = p2e["ens_opt"].update(e_grads, o_state["ensembles"], p["ensembles"])
            p = {**p, "ensembles": optax.apply_updates(p["ensembles"], e_updates)}
            o_state = {**o_state, "ensembles": new_e_opt}

        if p2e is not None:
            # exploration policy ("actor" — the one the player acts with)
            # learns the intrinsic return; the task policy learns extrinsic
            p, o_state, pl_e, vl_e = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_beh,
                actor_key="actor", critic_key="critic_exploration",
                target_key="target_critic_exploration", reward_kind="intrinsic",
            )
            p, o_state, pl_t, vl_t = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_task,
                actor_key="actor_task", critic_key="critic",
                target_key="target_critic", reward_kind="extrinsic",
            )
            pl, vl = pl_e + pl_t, vl_e + vl_t
        else:
            p, o_state, pl, vl = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_beh
            )

        # HARD target copy every target_freq updates (reference: dv2 value
        # target update)
        do_copy = (counter % target_freq) == 0
        p = {
            **p,
            "target_critic": jax.tree.map(
                lambda c, t: jnp.where(do_copy, c, t), p["critic"], p["target_critic"]
            ),
        }
        if p2e is not None:
            p = {
                **p,
                "target_critic_exploration": jax.tree.map(
                    lambda c, t: jnp.where(do_copy, c, t),
                    p["critic_exploration"], p["target_critic_exploration"]
                ),
            }
        post_ent = OneHotCategorical(jax.lax.stop_gradient(aux["post_logits"])).entropy().sum(-1).mean()
        prior_ent = OneHotCategorical(jax.lax.stop_gradient(aux["prior_logits"])).entropy().sum(-1).mean()
        metrics = (
            wm_l, aux["observation_loss"], aux["reward_loss"], aux["kl_loss"],
            aux["continue_loss"], aux["kl"], pl, vl, post_ent, prior_ent,
        )
        return (p, o_state, counter + 1), metrics

    def train_phase(p, o_state, blocks, k, counter0):
        U = blocks["rewards"].shape[0]
        keys = jax.random.split(k, U)
        (p, o_state, _), metrics = window_scan(
            single_update, (p, o_state, counter0), (blocks, keys), unroll=bool(cnn_keys)
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), metrics)

    in_sh = out_sh = None
    if params is not None and opt_state is not None:
        from sheeprl_tpu.parallel.compile import state_io_shardings
        from sheeprl_tpu.parallel.sharding import shardings_of

        in_sh, out_sh = state_io_shardings(
            shardings_of(params), shardings_of(opt_state), n_extra_in=3, n_extra_out=1
        )
    return fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        in_shardings=in_sh,
        out_shardings=out_sh,
        max_recompiles=cfg.algo.get("max_recompiles"),
    )


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    dreamer_family_loop(fabric, cfg, build_agent, make_train_phase)
