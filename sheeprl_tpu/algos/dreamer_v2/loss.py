"""DreamerV2 world-model loss, pure jittable math
(reference: sheeprl/algos/dreamer_v2/loss.py:9-85): α-balanced categorical
KL with free-avg free nats, Gaussian unit-variance reconstruction NLLs."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.distribution import OneHotCategorical, kl_categorical


def reconstruction_loss(
    obs_nll: jax.Array,
    reward_nll: jax.Array,
    continue_nll: Optional[jax.Array],
    posteriors_logits: jax.Array,
    priors_logits: jax.Array,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_regularizer: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """``obs_nll``/``reward_nll``/``continue_nll`` are per-step negative
    log-likelihoods of shape (L, B) (``continue_nll`` already scaled by the
    discount scale factor, or None when the continue head is disabled);
    posterior/prior logits are (L, B, stochastic, discrete)."""
    if continue_nll is None:
        continue_nll = jnp.zeros_like(reward_nll)
    post = OneHotCategorical(posteriors_logits)
    post_sg = OneHotCategorical(jax.lax.stop_gradient(posteriors_logits))
    prior = OneHotCategorical(priors_logits)
    prior_sg = OneHotCategorical(jax.lax.stop_gradient(priors_logits))
    # KL balancing (free-avg): each side clipped AFTER averaging
    lhs = kl_categorical(post_sg, prior).sum(-1)
    rhs = kl_categorical(post, prior_sg).sum(-1)
    kl = lhs
    loss_lhs = jnp.maximum(lhs.mean(), kl_free_nats)
    loss_rhs = jnp.maximum(rhs.mean(), kl_free_nats)
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    total = kl_regularizer * kl_loss + (obs_nll + reward_nll + continue_nll).mean()
    aux = {
        "kl": kl.mean(),
        "kl_loss": kl_loss,
        "observation_loss": obs_nll.mean(),
        "reward_loss": reward_nll.mean(),
        "continue_loss": continue_nll.mean(),
    }
    return total, aux
