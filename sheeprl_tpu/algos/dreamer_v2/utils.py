"""DreamerV2 utilities (reference: sheeprl/algos/dreamer_v2/utils.py)."""

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}
