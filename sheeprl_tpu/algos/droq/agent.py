"""DroQ agent (flax).

Capability parity with the reference (reference: sheeprl/algos/droq/agent.py:20-278):
SAC with a dropout + LayerNorm Q-ensemble (https://arxiv.org/abs/2110.02034)
enabling very high replay ratios.  Actor and temperature machinery are
shared with SAC.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.models.models import LayerNorm


class DroQCriticEnsemble(nn.Module):
    """N Q-functions with per-layer Dropout + LayerNorm, params-vmapped."""

    n_critics: int = 2
    hidden_size: int = 256
    dropout: float = 0.01
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, *, train: bool = False) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)

        class _OneQ(nn.Module):
            hidden: int
            dropout: float
            dtype: Any

            @nn.compact
            def __call__(self, x, train: bool):
                for i in range(2):
                    x = nn.Dense(self.hidden, dtype=self.dtype, name=f"dense_{i}")(x)
                    if self.dropout > 0:
                        x = nn.Dropout(self.dropout, deterministic=not train)(x)
                    x = LayerNorm(dtype=self.dtype, name=f"ln_{i}")(x)
                    x = nn.relu(x)
                return nn.Dense(1, dtype=jnp.float32, name="head")(x)

        q_net = nn.vmap(
            _OneQ,
            in_axes=(None, None),
            out_axes=0,
            axis_size=self.n_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )
        q = q_net(self.hidden_size, self.dropout, self.dtype, name="q_ensemble")(x, train)
        return q[..., 0]  # (N, B)


def build_agent(
    fabric: Any,
    act_dim: int,
    cfg: Any,
    obs_dim: int,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACActor, DroQCriticEnsemble, Dict[str, Any]]:
    actor = SACActor(
        act_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        dtype=fabric.precision.compute_dtype,
    )
    critic = DroQCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=float(cfg.algo.critic.dropout),
        dtype=fabric.precision.compute_dtype,
    )
    if state is not None:
        params = state
    else:
        k1, k2 = jax.random.split(jax.random.PRNGKey(cfg.seed))
        dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        actor_params = actor.init(k1, dummy_obs)
        critic_params = critic.init(k2, dummy_obs, dummy_act)
        params = {
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "log_alpha": jnp.asarray(np.log(cfg.algo.alpha.alpha), jnp.float32),
        }
    return actor, critic, fabric.replicate(params)
