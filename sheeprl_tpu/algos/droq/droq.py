"""DroQ — SAC with dropout/LayerNorm Q-ensembles and high replay ratio
(reference: sheeprl/algos/droq/droq.py:140-436).

Reuses the SAC engine with a dropout-active critic apply: DroQ's entire
algorithmic delta vs SAC is the critic regularization + replay_ratio=20
(reference derives it the same way).
"""

from __future__ import annotations

from typing import Any

from sheeprl_tpu.algos.droq.agent import build_agent
from sheeprl_tpu.algos.sac.sac import sac_loop
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    def dropout_apply(critic, cp, o, a, k):
        return critic.apply(cp, o, a, train=True, rngs={"dropout": k})

    sac_loop(fabric, cfg, build_agent, dropout_apply)
