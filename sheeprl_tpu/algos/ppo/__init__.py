"""PPO (coupled + decoupled) — TPU-native implementation."""
