"""PPO, coupled topology — the canonical end-to-end slice (SURVEY.md §7.2).

Capability parity with the reference train script
(reference: sheeprl/algos/ppo/ppo.py:30-453): vectorized env rollout with
truncation bootstrapping, GAE at rollout end, epoch/minibatch clipped-PPO
updates, polynomial annealing of lr/clip/entropy, policy-step-paced logging,
checkpointing and final test episode.

TPU-native architecture (not a port) — shaped by accelerator latency:
* **Host player / device trainer in one process.**  Action selection during
  rollout runs a jitted policy on the HOST CPU device against a params copy
  refreshed once per iteration.  Per-env-step accelerator round-trips are
  ~100ms on tunneled TPUs and never free; with a host player the rollout
  costs zero device syncs.  This is the single-process analogue of the
  reference's decoupled player/trainer topology
  (reference: sheeprl/algos/ppo/ppo_decoupled.py:32-365).
* **One dispatch per optimization phase.**  The full update — GAE, epoch
  loop, minibatch permutations, clipped losses, Adam — is a single jitted
  call (`lax.scan` over epochs × `lax.fori_loop` over minibatches on TPU;
  both levels unroll at trace time on XLA-CPU, where outlined loop bodies
  run ~5× slower — see `utils.window_scan`) with
  donated params: one host→device transfer of the rollout per iteration,
  one device→host transfer of the refreshed policy params.  The reference
  pays a DDP all-reduce + Python dispatch per minibatch instead.
* Parameters are replicated over the mesh and minibatches sharded over the
  ``data`` axis; XLA inserts the gradient all-reduce (DDP semantics without
  process groups).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, sample_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import (
    actions_for_env,
    normalize_obs_keys,
    obs_to_np,
    prepare_obs,
    spaces_to_dims,
    test,
)
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import stage_rollout, stage_scalar, steady_guard
from sheeprl_tpu.envs.jax.registry import anakin_enabled
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.utils import gae, normalize_tensor, polynomial_decay, save_configs, should_unroll_updates, window_scan


def epoch_permutation(
    key, T: int, B: int, batch_size: int, num_minibatches: int, share_data: bool, n_shards: int
) -> jax.Array:
    """Flat sample order for one PPO epoch over the (T, B) rollout, laid out
    as ``num_minibatches`` consecutive ``batch_size`` slices.

    * ``share_data=True`` (or one shard): one permutation of the GLOBAL
      (T·B) pool, padded by wrap-around to fill the last minibatch — the
      reference's all-gather + DistributedSampler pool semantics
      (reference: sheeprl/algos/ppo/ppo.py:363-370,41-47).
    * ``share_data=False`` with ``n_shards`` processes: classic DDP — each
      process permutes only ITS OWN env columns (process r owns columns
      [r·B/n, (r+1)·B/n), the shard_batch concatenation order) and every
      minibatch interleaves an equal ``batch_size/n_shards`` slice from each
      process, so the sample gather stays shard-local on a TPU mesh.
    """
    if share_data or n_shards == 1:
        perm = jax.random.permutation(key, T * B)
        pad = num_minibatches * batch_size - (T * B)
        return jnp.concatenate([perm, perm[: max(pad, 0)]]) if pad > 0 else perm
    b_loc = B // n_shards
    rows = T * b_loc
    pr_bs = batch_size // n_shards

    def rank_perm(kr, r):
        pl = jax.random.permutation(kr, rows)
        t_idx, b_idx = pl // b_loc, pl % b_loc
        return t_idx * B + r * b_loc + b_idx

    perms = jax.vmap(rank_perm)(jax.random.split(key, n_shards), jnp.arange(n_shards))
    pad = num_minibatches * pr_bs - rows
    if pad > 0:
        perms = jnp.concatenate([perms, perms[:, :pad]], axis=1)
    return (
        perms.reshape(n_shards, num_minibatches, pr_bs)
        .transpose(1, 0, 2)
        .reshape(num_minibatches * batch_size)
    )


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    # ---------------- environments -----------------------------------------
    num_envs = cfg.env.num_envs
    use_anakin = anakin_enabled(cfg, fabric)
    # population mode (docs/population.md): vmap whole agents over a
    # population axis INSIDE the fused Anakin executable, with in-trace PBT
    pop_size = int(cfg.get("population", {}).get("size", 0) or 0)
    use_population = pop_size > 1
    if use_population and not use_anakin:
        raise ValueError(
            "population.size>1 rides the Anakin axis: it needs a pure-JAX env "
            "(env=jax_*), algo.anakin != False, and a single-process run"
        )
    if use_anakin:
        # Anakin mode (envs/jax/anakin.py): the env lives INSIDE the
        # compiled update — no vector-env processes exist at all
        from sheeprl_tpu.envs.jax.core import VectorJaxEnv
        from sheeprl_tpu.envs.jax.registry import jax_env_from_cfg

        envs = None
        venv = VectorJaxEnv(jax_env_from_cfg(cfg), num_envs)
        obs_space = venv.single_observation_space
        act_space = venv.single_action_space
    else:
        envs = vectorize(
            cfg,
            [
                make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
                for i in range(num_envs)
            ],
        )
        obs_space = envs.single_observation_space
        act_space = envs.single_action_space
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    # ---------------- agent / optimizer -------------------------------------
    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space,
        # population checkpoints hold STACKED (P, ...) params — restored in
        # the population block below, not through the single-agent loader
        None if (use_population and state) else state.get("agent"),
    )
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    if use_population:
        opt_state = None  # stacked per-member init happens in the population block
    else:
        opt_state = fabric.replicate(state.get("opt_state") or optimizer.init(params))

    aggregator = MetricAggregator(
        cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {}
    )
    timer.configure(cfg.metric)

    # ---------------- host player (env-interaction policy) ------------------
    # on-policy loops honor algo.player.device (placement only; the sync
    # cadence options are meaningless on-policy: rollouts must use the
    # current weights)
    host = fabric.player_device(cfg)

    def policy_step_fn(p, obs, k, greedy=False):
        # key advances INSIDE the jitted step — one host dispatch per env
        # step instead of three (split/fold_in as separate tiny programs)
        k_sample, k_next = jax.random.split(k)
        out, value = agent.apply(p, obs)
        actions, logprob, _ = sample_actions(out, actions_dim, is_continuous, k_sample, greedy=greedy, dist_type=dist_type)
        return actions, logprob, value[..., 0], k_next

    # compile-once routing: AOT-compiled per abstract signature, counted by
    # the recompile detector (parallel/compile.py)
    policy_step_fn = fabric.compile(
        policy_step_fn,
        name=f"{cfg.algo.name}.policy_step",
        static_argnames=("greedy",),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    @jax.jit
    def values_fn(p, obs):
        _, value = agent.apply(p, obs)
        return value[..., 0]

    player_params = fabric.to_host(params)

    # ---------------- single-dispatch train phase ---------------------------
    reduction = cfg.algo.loss_reduction
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    vf_coef = float(cfg.algo.vf_coef)
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    update_epochs = int(cfg.algo.update_epochs)

    def loss_fn(p, batch, clip_coef, ent_coef):
        out, new_values = agent.apply(p, {k: batch[k] for k in obs_keys})
        new_logprobs, entropy = evaluate_actions(out, batch["actions"], actions_dim, is_continuous, dist_type=dist_type)
        adv = batch["advantages"]
        if normalize_adv:
            adv = normalize_tensor(adv)
        pg = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
        vl = value_loss(new_values[..., 0], batch["values"], batch["returns"], clip_coef, clip_vloss, reduction)
        ent = entropy_loss(entropy, reduction)
        return pg + vf_coef * vl + ent_coef * ent, (pg, vl, ent)

    def train_phase(
        p,
        o_state,
        rollout: Dict[str, jax.Array],
        last_obs: Dict[str, jax.Array],
        k,
        clip_coef,
        ent_coef,
        batch_size: int,
        num_minibatches: int,
        share_data: bool = True,
        n_shards: int = 1,
    ):
        """GAE + all epochs/minibatches in ONE device program.

        ``share_data`` selects the reference's two DP minibatch semantics
        (reference: sheeprl/algos/ppo/ppo.py:40-55,363-370):
        * True — every rank minibatches the GLOBAL rollout pool (the
          reference all-gathers + DistributedSampler); here a global
          permutation over the sharded (T·B) pool does it with no explicit
          gather — XLA moves only the rows each step needs.
        * False — classic DDP: each of the ``n_shards`` processes permutes
          only ITS OWN env columns and contributes ``batch_size/n_shards``
          rows per step; the sample gather stays shard-local (no cross-host
          traffic), gradients combine exactly as DDP's all-reduce would.
        """
        # --- GAE (values recomputed in one batched forward) ---
        T, B = rollout["rewards"].shape
        flat_obs = {key_: rollout[key_].reshape((T * B,) + rollout[key_].shape[2:]) for key_ in obs_keys}
        _, values = agent.apply(p, flat_obs)
        values = values[..., 0].reshape(T, B)
        next_value = values_fn(p, last_obs)
        returns, advantages = gae(
            rollout["rewards"], values, rollout["dones"], next_value, gamma, gae_lambda
        )

        flat = dict(flat_obs)
        flat["actions"] = rollout["actions"].reshape(T * B, -1)
        flat["logprobs"] = rollout["logprobs"].reshape(T * B)
        flat["values"] = values.reshape(T * B)
        flat["returns"] = returns.reshape(T * B)
        flat["advantages"] = advantages.reshape(T * B)

        # XLA-CPU runs conv-bearing bodies ~5x slower inside outlined loops
        # (scan/fori — see utils.window_scan); unroll BOTH update levels at
        # trace time when the total body count is small enough to compile
        unroll_updates = should_unroll_updates(cnn_keys, update_epochs * num_minibatches)

        def epoch_body(carry, key_e):
            p, o_state = carry
            perm = epoch_permutation(
                key_e, T, B, batch_size, num_minibatches, share_data, n_shards
            )

            def mb_body(i, carry2):
                p, o_state, losses = carry2
                idx = jax.lax.dynamic_slice(perm, (i * batch_size,), (batch_size,))
                batch = {kk: jnp.take(vv, idx, axis=0) for kk, vv in flat.items()}
                (_, (pg, vl, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    p, batch, clip_coef, ent_coef
                )
                updates, o_state = optimizer.update(grads, o_state, p)
                p = optax.apply_updates(p, updates)
                return p, o_state, (pg, vl, ent)

            carry2 = (p, o_state, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(())))
            if unroll_updates:
                for i in range(num_minibatches):
                    carry2 = mb_body(i, carry2)
                p, o_state, losses = carry2
            else:
                p, o_state, losses = jax.lax.fori_loop(
                    0, num_minibatches, mb_body, carry2
                )
            return (p, o_state), losses

        (p, o_state), losses = window_scan(
            epoch_body,
            (p, o_state),
            jax.random.split(k, update_epochs),
            unroll_limit=32,
            unroll=unroll_updates,
        )
        last_losses = jax.tree.map(lambda x: x[-1], losses)
        return p, o_state, last_losses

    # donate the STAGED rollout and bootstrap obs too (argnums 2/3): the one
    # dispatch consumes them exactly once, so XLA recycles their HBM for
    # activations instead of holding a dead copy across the update
    train_phase_fn = train_phase  # raw callable: the Anakin path fuses it
    train_phase = fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1, 2, 3),
        static_argnames=("batch_size", "num_minibatches", "share_data", "n_shards"),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    # ---------------- counters / schedules ----------------------------------
    # the train phase is a GLOBAL program: its batch covers all ranks
    sharded_envs, global_envs = fabric.env_sharding_plan(num_envs, "PPO")
    rollout_steps = int(cfg.algo.rollout_steps)
    T, B = rollout_steps, global_envs
    global_bs = min(int(cfg.algo.per_rank_batch_size) * fabric.world_size, T * B)
    num_minibatches = -(-T * B // global_bs)  # ceil: keep the tail
    # reference semantics (ppo.py:363-370): share_data only changes anything
    # across processes; the per-process shards must admit equal batch slices
    share_data = bool(cfg.buffer.get("share_data", False))
    n_shards = fabric.num_processes if sharded_envs else 1
    if n_shards > 1 and (global_bs % n_shards or B % n_shards):
        if not share_data:
            # share_data=False is the SHIPPED default (configs/exp/ppo.yaml),
            # so a hard error here would abort previously-working runs; the
            # fallback is instead documented in howto/configs.md (ADVICE r4)
            import warnings

            warnings.warn(
                f"buffer.share_data=False needs equal per-process batch slices "
                f"(batch {global_bs}, envs {B}, processes {n_shards}): falling "
                "back to the global-pool (share_data=True) sampler — pick a "
                "divisible algo.per_rank_batch_size/env.num_envs to keep "
                "shard-local sampling (see howto/configs.md)"
            )
        n_shards = 1  # uneven split: fall back to the global-pool sampler
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * rollout_steps * fabric.num_processes
    if use_population:
        # every member steps its own env shard: the population multiplies
        # the env steps per fused update, so total_steps buys fewer updates
        policy_steps_per_iter *= pop_size
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))

    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)
    base_lr = float(cfg.algo.optimizer.lr)
    clip_coef_v = initial_clip_coef
    ent_coef_v = initial_ent_coef
    # arm jax.transfer_guard("disallow") around steady-state train dispatches
    # (all staging above is explicit device_put, so the guard passing proves
    # the zero-implicit-H2D contract end to end)
    guard_on = bool(cfg.buffer.get("transfer_guard", False))

    # ---------------- Anakin fused rollout+train ----------------------------
    if use_anakin:
        from sheeprl_tpu.envs.jax.anakin import (
            init_actor_state,
            make_rollout_fn,
            traced_polynomial_decay,
        )

        def _sample(out, k):
            return sample_actions(out, actions_dim, is_continuous, k, dist_type=dist_type)

        rollout_fn = make_rollout_fn(
            venv,
            agent.apply,
            _sample,
            cnn_keys=cnn_keys,
            mlp_keys=mlp_keys,
            action_space=act_space,
            gamma=gamma,
            rollout_steps=rollout_steps,
        )

        def anakin_phase(p, o_state, actor, k):
            """``lax.scan`` env rollout + GAE + all epochs/minibatches in
            ONE device program.  Annealed coefficients are computed
            in-trace from the donated update counter, so the steady state
            performs zero host-to-device transfers of any kind."""
            k_roll, k_train, k_next = jax.random.split(k, 3)
            step0 = actor["update"]
            clip = (
                traced_polynomial_decay(step0, initial=initial_clip_coef, max_decay_steps=total_iters)
                if cfg.algo.anneal_clip_coef
                else jnp.float32(initial_clip_coef)
            )
            ent = (
                traced_polynomial_decay(step0, initial=initial_ent_coef, max_decay_steps=total_iters)
                if cfg.algo.anneal_ent_coef
                else jnp.float32(initial_ent_coef)
            )
            if cfg.algo.anneal_lr:
                o_state = set_learning_rate(
                    o_state,
                    traced_polynomial_decay(step0, initial=base_lr, max_decay_steps=total_iters, power=1.0),
                )
            actor, rollout, last_obs, stats = rollout_fn(p, actor, k_roll)
            p, o_state, losses = train_phase_fn(
                p,
                o_state,
                rollout,
                last_obs,
                k_train,
                clip,
                ent,
                batch_size=global_bs,
                num_minibatches=num_minibatches,
                share_data=share_data,
                n_shards=n_shards,
            )
            return p, o_state, actor, k_next, losses, stats

        if use_population:
            # ------------ population: vmap whole agents over P ------------
            from sheeprl_tpu import telemetry
            from sheeprl_tpu.population import (
                PBTConfig,
                PopulationMonitor,
                init_population_state,
                make_population_phase,
                tile_stack,
                write_population_summary,
            )

            pbt_cfg = PBTConfig.from_cfg(
                cfg,
                base={"lr": base_lr, "ent_coef": initial_ent_coef, "clip_coef": initial_clip_coef},
            )

            def member_phase(p, o_state, actor, k, hp):
                """ONE member's fused rollout+train with its hyperparameters
                as traced data (lr through the injected opt-state, clip/ent
                into the loss).  PBT replaces the anneal schedules, so the
                ``algo.anneal_*`` flags are inert in population mode."""
                k_roll, k_train = jax.random.split(k)
                o_state = set_learning_rate(o_state, hp["lr"])
                actor, rollout, last_obs, stats = rollout_fn(p, actor, k_roll)
                p, o_state, losses = train_phase_fn(
                    p,
                    o_state,
                    rollout,
                    last_obs,
                    k_train,
                    hp["clip_coef"],
                    hp["ent_coef"],
                    batch_size=global_bs,
                    num_minibatches=num_minibatches,
                    share_data=share_data,
                    n_shards=1,  # population runs are single-process (enforced above)
                )
                return p, o_state, actor, losses, stats

            population_step = fabric.compile(
                make_population_phase(member_phase, pbt_cfg),
                name=f"{cfg.algo.name}.population_phase",
                donate_argnums=(0, 1, 2, 3),
                max_recompiles=cfg.algo.get("max_recompiles"),
            )

            # stacked member state: all members start from the SAME init
            # (the hyperparameter spread diversifies them); opt-state is
            # per-member so exploit can copy weights+moments coherently;
            # each member gets its own seeded env shard
            pop_resume = state.get("population") if state else None
            if state:
                params = fabric.replicate(jax.tree.map(jnp.asarray, state["agent"]))
                opt_state = fabric.replicate(state["opt_state"])
            else:
                params = jax.device_put(tile_stack(params, pop_size), fabric.replicated)
                opt_state = jax.device_put(jax.vmap(optimizer.init)(params), fabric.replicated)

            def _init_member(k):
                env_state, _ = venv.reset(k)
                return {
                    "env": env_state,
                    "ep_ret": jnp.zeros((num_envs,), jnp.float32),
                    "ep_len": jnp.zeros((num_envs,), jnp.int32),
                }

            members = jax.vmap(_init_member)(
                jax.random.split(jax.random.fold_in(key, fabric.global_rank + 1), pop_size)
            )
            members["update"] = jnp.full((pop_size,), start_iter - 1, jnp.int32)
            pop_state = init_population_state(members, pbt_cfg, num_envs)
            if pop_resume:
                pop_state["fitness"] = jnp.asarray(pop_resume["fitness"])
                pop_state["ep_count"] = jnp.asarray(pop_resume["ep_count"])
                pop_state["exploits"] = jnp.asarray(pop_resume["exploits"])
                hp_state = {name: jnp.asarray(v) for name, v in pop_resume["hp"].items()}
            else:
                hp_state = pbt_cfg.init_hyperparams(jax.random.fold_in(key, pop_size))
            pop_state = jax.device_put(pop_state, fabric.replicated)
            hp_state = jax.device_put(hp_state, fabric.replicated)
            pop_monitor = PopulationMonitor()
            telemetry.HUB.register("population", pop_monitor)
            anakin_step = None
            actor_state = None
        else:
            anakin_step = fabric.compile(
                anakin_phase,
                name=f"{cfg.algo.name}.anakin_phase",
                donate_argnums=(0, 1, 2),
                max_recompiles=cfg.algo.get("max_recompiles"),
            )
            actor_state = init_actor_state(
                fabric, venv, jax.random.fold_in(key, fabric.global_rank + 1), start_iter - 1, sharded_envs
            )
        rb = None
    else:
        rb = ReplayBuffer(
            rollout_steps,
            num_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
            obs_keys=obs_keys,
        )

    # ---------------- main loop ---------------------------------------------
    step_data: Dict[str, np.ndarray] = {}
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    if envs is not None:
        obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    last_losses = None
    # per-rank player key stream, advanced inside policy_step_fn; the main
    # `key` stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    from sheeprl_tpu.utils.profiler import ProfilerGate

    profiler = ProfilerGate(cfg, log_dir)
    for update in range(start_iter, total_iters + 1):
        profiler.step(update)
        if use_anakin:
            # -------- fused rollout+train: ONE dispatch per update ---------
            with timer("Time/train_time"):
                with steady_guard(guard_on and update > start_iter):
                    if use_population:
                        # the WHOLE population trains in this one dispatch
                        params, opt_state, pop_state, hp_state, key, last_losses, ep_stats = (
                            population_step(params, opt_state, pop_state, hp_state, key)
                        )
                    else:
                        params, opt_state, actor_state, key, last_losses, ep_stats = anakin_step(
                            params, opt_state, actor_state, key
                        )
                if use_population:
                    # per-member (P,) losses → scalars for the aggregator
                    last_losses = jax.tree.map(lambda x: x.mean(), last_losses)
                policy_step += policy_steps_per_iter
            if cfg.metric.log_level > 0:
                # completion arrays are tiny; the pull is D2H (legal under
                # the H2D-scoped steady guard)
                from sheeprl_tpu.envs.jax.anakin import episode_stats_from_device

                rets, lens = episode_stats_from_device(ep_stats)
                for ep_ret, ep_len in zip(rets, lens):
                    aggregator.update("Rewards/rew_avg", float(ep_ret))
                    aggregator.update("Game/ep_len_avg", int(ep_len))
                if use_population:
                    # Population/* hub family: tiny D2H pulls on the logging
                    # cadence (the guard is H2D-scoped)
                    pop_monitor.observe(
                        pop_state["fitness"], hp_state, pop_state["exploits"]
                    )
        else:
            with timer("Time/env_interaction_time"):
                with jax.default_device(host):
                    for _ in range(rollout_steps):
                        policy_step += num_envs * fabric.num_processes

                        dev_obs = prepare_obs(obs, cnn_keys, mlp_keys)
                        actions, logprobs, _, player_key = policy_step_fn(
                            player_params, dev_obs, player_key
                        )
                        actions_np = np.asarray(actions)
                        next_obs, rewards, terminated, truncated, info = envs.step(
                            actions_for_env(actions_np, act_space)
                        )
                        dones = np.logical_or(terminated, truncated)
                        rewards = np.asarray(rewards, np.float32)

                        # truncation bootstrap: r += γ·V(real final obs)
                        # (reference: ppo.py:287-306).  The final-obs batch is
                        # padded to the full env count so values_fn keeps ONE
                        # static shape (no per-count recompiles).
                        if np.any(truncated):
                            final_obs = final_obs_rows(info, np.nonzero(truncated)[0], obs_keys)
                            if final_obs is not None:
                                padded = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
                                for k in obs_keys:
                                    padded[k][truncated] = final_obs[k]
                                vals = np.asarray(
                                    values_fn(player_params, prepare_obs(padded, cnn_keys, mlp_keys))
                                )
                                rewards[truncated] += gamma * vals[truncated]

                        for k in obs_keys:
                            step_data[k] = np.asarray(obs[k])[None]
                        step_data["actions"] = actions_np[None]
                        step_data["logprobs"] = np.asarray(logprobs)[None]
                        # values are NOT stored: train_phase recomputes them with
                        # the same (unchanged) params in one batched forward
                        step_data["rewards"] = rewards[None]
                        step_data["dones"] = dones[None].astype(np.float32)
                        rb.add({k: v[..., None] if v.ndim == 2 else v for k, v in step_data.items()})

                        obs = next_obs
                        for ep_ret, ep_len in episode_stats(info):
                            aggregator.update("Rewards/rew_avg", ep_ret)
                            aggregator.update("Game/ep_len_avg", ep_len)

            # ---------------- one-dispatch optimization -------------------------
            with timer("Time/train_time"):
                # donated device staging: the rollout is normalized on HOST
                # numpy, staged with EXPLICIT device_puts (transfer-guard-clean,
                # data/device_replay.stage_rollout) and donated into the train
                # phase, which consumes it exactly once per dispatch — its HBM is
                # recycled for activations.  buffer.transfer_guard=true arms
                # jax.transfer_guard("disallow") around the dispatch to prove no
                # implicit H2D rides along.
                local = rb.buffer
                host_rollout = {k: obs_to_np(local[k], k in cnn_keys, rollout=True) for k in obs_keys}
                host_rollout["actions"] = np.asarray(local["actions"])
                host_rollout["logprobs"] = np.asarray(local["logprobs"][..., 0])
                host_rollout["rewards"] = np.asarray(local["rewards"][..., 0])
                host_rollout["dones"] = np.asarray(local["dones"][..., 0])
                # multi-host: each process contributes its local env rows and the
                # global batch is their concatenation (axis=1); single-process
                # replicates (env-axis minibatch gathers are cheapest replicated)
                rollout = stage_rollout(fabric, host_rollout, axis=1, sharded=sharded_envs)
                host_last = {k: obs_to_np(np.asarray(obs[k]), k in cnn_keys) for k in obs_keys}
                last_obs_dev = stage_rollout(fabric, host_last, axis=0, sharded=sharded_envs)
                key, tk = jax.random.split(key)
                clip_dev = stage_scalar(clip_coef_v)
                ent_dev = stage_scalar(ent_coef_v)
                with steady_guard(guard_on and update > start_iter):
                    params, opt_state, last_losses = train_phase(
                        params,
                        opt_state,
                        rollout,
                        last_obs_dev,
                        tk,
                        clip_dev,
                        ent_dev,
                        batch_size=global_bs,
                        num_minibatches=num_minibatches,
                        share_data=share_data,
                        n_shards=n_shards,
                    )
                # refresh the host player once per iteration (one d2h transfer)
                player_params = fabric.to_host(params)

        # ---------------- schedules -----------------------------------------
        # (Anakin mode anneals in-trace from the donated update counter —
        # host-side annealing would be a per-update H2D write)
        if not use_anakin:
            if cfg.algo.anneal_lr:
                new_lr = polynomial_decay(update, initial=base_lr, final=0.0, max_decay_steps=total_iters, power=1.0)
                opt_state = set_learning_rate(opt_state, new_lr)
            if cfg.algo.anneal_clip_coef:
                clip_coef_v = polynomial_decay(update, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters)
            if cfg.algo.anneal_ent_coef:
                ent_coef_v = polynomial_decay(update, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters)

        # ---------------- logging --------------------------------------------
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                pg, vl, ent = last_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", ent)
            last_log = flush_metrics(aggregator, timer, logger, policy_step, last_log)

        # ---------------- checkpoint -----------------------------------------
        # cadence + final save_last + preemption, via the fault-tolerant
        # subsystem (async snapshot → durable commit; docs/checkpointing.md)
        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": global_bs,
            }
            if use_population:
                # params/opt_state above are already the stacked (P, ...)
                # pytrees; the PBT carry rides its own subtree
                ckpt_state["population"] = {
                    "fitness": pop_state["fitness"],
                    "ep_count": pop_state["ep_count"],
                    "exploits": pop_state["exploits"],
                    "hp": hp_state,
                }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    profiler.close()
    if envs is not None:
        envs.close()
    ckpt_mgr.finalize()
    if use_population and fabric.is_global_zero:
        # machine-readable member snapshot for the run_ci PBT drill and
        # bench --mode population
        write_population_summary(log_dir, pop_state, hp_state, policy_step)
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        if use_population:
            # eval the current BEST member (fitness argmax)
            best = int(np.asarray(pop_state["fitness"]).argmax())
            player_params = fabric.to_host(jax.tree.map(lambda x: x[best], params))
        elif use_anakin:
            # the fused path never refreshes the host player copy — pull
            # the final params once for the eval episode
            player_params = fabric.to_host(params)
        test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()


def _obs_to_device(arr: np.ndarray, is_image: bool) -> jax.Array:
    from sheeprl_tpu.algos.ppo.utils import obs_to_np

    return jnp.asarray(obs_to_np(arr, is_image, rollout=True))
