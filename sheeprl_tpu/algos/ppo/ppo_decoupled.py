"""PPO, decoupled (player/learner-overlapped) topology.

Capability parity with the reference's decoupled PPO
(reference: sheeprl/algos/ppo/ppo_decoupled.py:32-670): env interaction and
optimization proceed concurrently, with the player acting on slightly stale
policy weights while trainers optimize.

The reference implements this with N processes and three TorchCollective
groups (world scatter, player↔trainer-1 weight broadcast, trainer DDP
group).  The TPU-native equivalent needs NO process groups: JAX dispatch is
asynchronous, so the single controller

  1. dispatches the (donated, jitted) train phase for rollout *k* — the call
     returns immediately while the device crunches;
  2. collects rollout *k+1* on the host with the player params of rollout
     *k-1* (a one-iteration staleness, same semantics as the reference's
     player acting during trainer optimization);
  3. then syncs the refreshed params to the host player — by which time the
     device is done, so the transfer is the only wait.

Gradient all-reduce across the mesh happens inside the jitted step (GSPMD),
playing the role of the trainer DDP subgroup.  `fabric.devices` therefore
still scales training exactly like adding trainer ranks in the reference.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, sample_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import (
    actions_for_env,
    normalize_obs_keys,
    prepare_obs,
    spaces_to_dims,
    test,
)
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.utils import polynomial_decay
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, save_configs


@register_algorithm(decoupled=True, name="ppo_decoupled")
def main(fabric: Any, cfg: Any) -> None:
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = vectorize(
        cfg,
        [
            make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
            for i in range(num_envs)
        ],
    )
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent"))
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    opt_state = fabric.replicate(state.get("opt_state") or optimizer.init(params))

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.disabled = cfg.metric.disable_timer or cfg.metric.log_level == 0

    # on-policy loops honor algo.player.device (placement only; the sync
    # cadence options are meaningless on-policy: rollouts must use the
    # current weights)
    host = fabric.player_device(cfg)
    reduction = cfg.algo.loss_reduction
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    vf_coef = float(cfg.algo.vf_coef)
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    update_epochs = int(cfg.algo.update_epochs)

    @jax.jit
    def policy_step_fn(p, obs, k):
        out, value = agent.apply(p, obs)
        actions, logprob, _ = sample_actions(out, actions_dim, is_continuous, k, dist_type=dist_type)
        return actions, logprob, value[..., 0]

    @jax.jit
    def values_fn(p, obs):
        _, value = agent.apply(p, obs)
        return value[..., 0]

    def loss_fn(p, batch, clip_coef, ent_coef):
        out, new_values = agent.apply(p, {k: batch[k] for k in obs_keys})
        new_logprobs, entropy = evaluate_actions(out, batch["actions"], actions_dim, is_continuous, dist_type=dist_type)
        adv = batch["advantages"]
        if normalize_adv:
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
        vl = value_loss(new_values[..., 0], batch["values"], batch["returns"], clip_coef, clip_vloss, reduction)
        ent = entropy_loss(entropy, reduction)
        return pg + vf_coef * vl + ent_coef * ent, (pg, vl, ent)

    @partial(jax.jit, donate_argnums=(0, 1), static_argnames=("batch_size", "num_minibatches"))
    def train_phase(p, o_state, rollout, last_obs, k, clip_coef, ent_coef, batch_size, num_minibatches):
        T, B = rollout["rewards"].shape
        flat_obs = {kk: rollout[kk].reshape((T * B,) + rollout[kk].shape[2:]) for kk in obs_keys}
        _, values = agent.apply(p, flat_obs)
        values = values[..., 0].reshape(T, B)
        next_value = values_fn(p, last_obs)
        returns, advantages = gae(rollout["rewards"], values, rollout["dones"], next_value, gamma, gae_lambda)
        flat = dict(flat_obs)
        flat["actions"] = rollout["actions"].reshape(T * B, -1)
        flat["logprobs"] = rollout["logprobs"].reshape(T * B)
        flat["values"] = values.reshape(T * B)
        flat["returns"] = returns.reshape(T * B)
        flat["advantages"] = advantages.reshape(T * B)

        def epoch_body(carry, key_e):
            p, o_state = carry
            perm = jax.random.permutation(key_e, T * B)
            pad = num_minibatches * batch_size - (T * B)
            perm = jnp.concatenate([perm, perm[: max(pad, 0)]]) if pad > 0 else perm

            def mb_body(i, carry2):
                p, o_state, _ = carry2
                idx = jax.lax.dynamic_slice(perm, (i * batch_size,), (batch_size,))
                batch = {kk: jnp.take(vv, idx, axis=0) for kk, vv in flat.items()}
                (_, (pg, vl, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    p, batch, clip_coef, ent_coef
                )
                updates, o_state = optimizer.update(grads, o_state, p)
                p = optax.apply_updates(p, updates)
                return p, o_state, (pg, vl, ent)

            p, o_state, losses = jax.lax.fori_loop(
                0, num_minibatches, mb_body,
                (p, o_state, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))),
            )
            return (p, o_state), losses

        (p, o_state), losses = jax.lax.scan(epoch_body, (p, o_state), jax.random.split(k, update_epochs))
        return p, o_state, jax.tree.map(lambda x: x[-1], losses)

    rollout_steps = int(cfg.algo.rollout_steps)
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * rollout_steps * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    clip_coef_v = float(cfg.algo.clip_coef)
    ent_coef_v = float(cfg.algo.ent_coef)

    rb = ReplayBuffer(rollout_steps, num_envs, memmap=False, obs_keys=obs_keys)

    def collect_rollout(obs, player_params, key):
        """One rollout with the (possibly stale) player params."""
        nonlocal policy_step
        with jax.default_device(host):
            for _ in range(rollout_steps):
                policy_step += num_envs * fabric.num_processes
                dev_obs = prepare_obs(obs, cnn_keys, mlp_keys)
                key, sk = jax.random.split(key)
                # per-rank sampling: the shared key stream stays rank-identical
                # (train-dispatch keys must agree across processes), so fold the
                # rank into the PLAYER key only
                sk = jax.random.fold_in(sk, rank)
                actions, logprobs, _ = policy_step_fn(player_params, dev_obs, sk)
                actions_np = np.asarray(actions)
                next_obs, rewards, terminated, truncated, info = envs.step(
                    actions_for_env(actions_np, act_space)
                )
                dones = np.logical_or(terminated, truncated)
                rewards = np.asarray(rewards, np.float32)
                if np.any(truncated):
                    final_obs = final_obs_rows(info, np.nonzero(truncated)[0], obs_keys)
                    if final_obs is not None:
                        padded = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
                        for k in obs_keys:
                            padded[k][truncated] = final_obs[k]
                        vals = np.asarray(values_fn(player_params, prepare_obs(padded, cnn_keys, mlp_keys)))
                        rewards[truncated] += gamma * vals[truncated]
                step_data = {}
                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[None]
                step_data["actions"] = actions_np[None]
                step_data["logprobs"] = np.asarray(logprobs)[None]
                step_data["rewards"] = rewards[None]
                step_data["dones"] = dones[None].astype(np.float32)
                rb.add({k: v[..., None] if v.ndim == 2 else v for k, v in step_data.items()})
                obs = next_obs
                for ep_ret, ep_len in episode_stats(info):
                    aggregator.update("Rewards/rew_avg", ep_ret)
                    aggregator.update("Game/ep_len_avg", ep_len)
        from sheeprl_tpu.algos.ppo.ppo import _obs_to_device

        local = rb.buffer
        rollout = {}
        for k in obs_keys:
            rollout[k] = _obs_to_device(local[k], k in cnn_keys)
        rollout["actions"] = jnp.asarray(local["actions"])
        rollout["logprobs"] = jnp.asarray(local["logprobs"][..., 0])
        rollout["rewards"] = jnp.asarray(local["rewards"][..., 0])
        rollout["dones"] = jnp.asarray(local["dones"][..., 0])
        return obs, rollout, key

    # the train phase is a GLOBAL program: its batch covers all ranks
    sharded_envs, B = fabric.env_sharding_plan(num_envs, "decoupled PPO")
    T = rollout_steps
    global_bs = min(int(cfg.algo.per_rank_batch_size) * fabric.world_size, T * B)
    num_minibatches = -(-T * B // global_bs)

    def ship(rollout, axis=1):
        if sharded_envs:
            return fabric.shard_batch(rollout, axis=axis)
        return fabric.replicate(rollout)

    # ---------------- pipelined main loop -----------------------------------
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    player_params = fabric.to_host(params)
    last_losses = None

    with timer("Time/env_interaction_time"):
        obs, rollout, key = collect_rollout(obs, player_params, key)

    for update in range(start_iter, total_iters + 1):
        # 1. dispatch training for rollout k (async — returns immediately)
        with timer("Time/train_time"):
            key, tk = jax.random.split(key)
            params, opt_state, last_losses = train_phase(
                params, opt_state, ship(rollout),
                ship(prepare_obs(obs, cnn_keys, mlp_keys), axis=0),
                tk, jnp.float32(clip_coef_v), jnp.float32(ent_coef_v),
                batch_size=global_bs, num_minibatches=num_minibatches,
            )
        # 2. collect rollout k+1 with the stale player while the device trains
        if update < total_iters:
            with timer("Time/env_interaction_time"):
                obs, rollout, key = collect_rollout(obs, player_params, key)
        # 3. refresh the player (device is done by now; transfer is the wait)
        player_params = fabric.to_host(params)

        # schedules (reference: ppo_decoupled.py:586-594)
        if cfg.algo.anneal_lr:
            opt_state = set_learning_rate(
                opt_state,
                polynomial_decay(update, initial=float(cfg.algo.optimizer.lr), final=0.0, max_decay_steps=total_iters),
            )
        if cfg.algo.anneal_clip_coef:
            clip_coef_v = polynomial_decay(
                update, initial=float(cfg.algo.clip_coef), final=0.0, max_decay_steps=total_iters
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef_v = polynomial_decay(
                update, initial=float(cfg.algo.ent_coef), final=0.0, max_decay_steps=total_iters
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                pg, vl, ent = last_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", ent)
            metrics = aggregator.compute()
            aggregator.reset()
            times = timer.to_dict(reset=True)
            steps_since = max(policy_step - last_log, 1)
            if "Time/env_interaction_time" in times:
                metrics["Time/sps_env_interaction"] = steps_since / max(times["Time/env_interaction_time"], 1e-9)
            if "Time/train_time" in times:
                metrics["Time/sps_train"] = steps_since / max(times["Time/train_time"], 1e-9)
            metrics.update(times)
            if logger is not None and metrics:
                logger.log_metrics(metrics, policy_step)
            last_log = policy_step

        if (
            cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every
        ) or (update == total_iters and cfg.checkpoint.save_last):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            fabric.call(
                "on_checkpoint_player",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
            )

    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
