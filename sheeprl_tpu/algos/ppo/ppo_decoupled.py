"""PPO, decoupled (player/learner-overlapped) topology.

Capability parity with the reference's decoupled PPO
(reference: sheeprl/algos/ppo/ppo_decoupled.py:32-670): env interaction and
optimization proceed concurrently, with the player acting on slightly stale
policy weights while trainers optimize.

The reference implements this with N processes and three TorchCollective
groups (world scatter, player↔trainer-1 weight broadcast, trainer DDP
group).  The TPU-native equivalent needs NO process groups: JAX dispatch is
asynchronous, so the single controller

  1. dispatches the (donated, jitted) train phase for rollout *k* — the call
     returns immediately while the device crunches;
  2. collects rollout *k+1* on the host with the player params of rollout
     *k-1* (a one-iteration staleness, same semantics as the reference's
     player acting during trainer optimization);
  3. then syncs the refreshed params to the host player — by which time the
     device is done, so the transfer is the only wait.

Gradient all-reduce across the mesh happens inside the jitted step (GSPMD),
playing the role of the trainer DDP subgroup.  `fabric.devices` therefore
still scales training exactly like adding trainer ranks in the reference.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, sample_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import (
    actions_for_env,
    normalize_obs_keys,
    obs_to_np,
    prepare_obs,
    spaces_to_dims,
    test,
)
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.parallel.compile import compile_once
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.utils import normalize_tensor, polynomial_decay
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, save_configs, should_unroll_updates, window_scan


def _build_train_fns(agent, optimizer, cfg, obs_keys, actions_dim, is_continuous, dist_type):
    """The jitted policy/value/train-phase programs shared by the pipelined
    (single-controller) and dedicated (cross-process) decoupled topologies."""
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    reduction = cfg.algo.loss_reduction
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    vf_coef = float(cfg.algo.vf_coef)
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    update_epochs = int(cfg.algo.update_epochs)

    def policy_step_fn(p, obs, k):
        # key advances INSIDE the jitted step (one host dispatch per env step)
        k_sample, k_next = jax.random.split(k)
        out, value = agent.apply(p, obs)
        actions, logprob, _ = sample_actions(out, actions_dim, is_continuous, k_sample, dist_type=dist_type)
        return actions, logprob, value[..., 0], k_next

    # compile-once routing (no fabric in scope for this shared builder:
    # use the module-level constructor directly)
    policy_step_fn = compile_once(
        policy_step_fn,
        name=f"{cfg.algo.name}.policy_step",
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    @jax.jit
    def values_fn(p, obs):
        _, value = agent.apply(p, obs)
        return value[..., 0]

    def loss_fn(p, batch, clip_coef, ent_coef):
        out, new_values = agent.apply(p, {k: batch[k] for k in obs_keys})
        new_logprobs, entropy = evaluate_actions(out, batch["actions"], actions_dim, is_continuous, dist_type=dist_type)
        adv = batch["advantages"]
        if normalize_adv:
            adv = normalize_tensor(adv)
        pg = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
        vl = value_loss(new_values[..., 0], batch["values"], batch["returns"], clip_coef, clip_vloss, reduction)
        ent = entropy_loss(entropy, reduction)
        return pg + vf_coef * vl + ent_coef * ent, (pg, vl, ent)

    def train_phase(p, o_state, rollout, last_obs, k, clip_coef, ent_coef, batch_size, num_minibatches):
        T, B = rollout["rewards"].shape
        flat_obs = {kk: rollout[kk].reshape((T * B,) + rollout[kk].shape[2:]) for kk in obs_keys}
        _, values = agent.apply(p, flat_obs)
        values = values[..., 0].reshape(T, B)
        next_value = values_fn(p, last_obs)
        returns, advantages = gae(rollout["rewards"], values, rollout["dones"], next_value, gamma, gae_lambda)
        flat = dict(flat_obs)
        flat["actions"] = rollout["actions"].reshape(T * B, -1)
        flat["logprobs"] = rollout["logprobs"].reshape(T * B)
        flat["values"] = values.reshape(T * B)
        flat["returns"] = returns.reshape(T * B)
        flat["advantages"] = advantages.reshape(T * B)

        # XLA-CPU outlined-loop penalty is conv-specific: see
        # utils.window_scan / should_unroll_updates
        unroll_updates = should_unroll_updates(cnn_keys, update_epochs * num_minibatches)

        def epoch_body(carry, key_e):
            p, o_state = carry
            perm = jax.random.permutation(key_e, T * B)
            pad = num_minibatches * batch_size - (T * B)
            perm = jnp.concatenate([perm, perm[: max(pad, 0)]]) if pad > 0 else perm

            def mb_body(i, carry2):
                p, o_state, _ = carry2
                idx = jax.lax.dynamic_slice(perm, (i * batch_size,), (batch_size,))
                batch = {kk: jnp.take(vv, idx, axis=0) for kk, vv in flat.items()}
                (_, (pg, vl, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    p, batch, clip_coef, ent_coef
                )
                updates, o_state = optimizer.update(grads, o_state, p)
                p = optax.apply_updates(p, updates)
                return p, o_state, (pg, vl, ent)

            carry2 = (p, o_state, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(())))
            if unroll_updates:
                for i in range(num_minibatches):
                    carry2 = mb_body(i, carry2)
                p, o_state, losses = carry2
            else:
                p, o_state, losses = jax.lax.fori_loop(0, num_minibatches, mb_body, carry2)
            return (p, o_state), losses

        (p, o_state), losses = window_scan(
            epoch_body,
            (p, o_state),
            jax.random.split(k, update_epochs),
            unroll_limit=32,
            unroll=unroll_updates,
        )
        return p, o_state, jax.tree.map(lambda x: x[-1], losses)

    train_phase_raw = train_phase  # the sebulba learner fuses it (concat + GAE + epochs)
    train_phase = compile_once(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        static_argnames=("batch_size", "num_minibatches"),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    return policy_step_fn, values_fn, train_phase, train_phase_raw


def _run_rollout(ctx, obs, p_params, key, fold_rank=None):
    """THE env-interaction rollout loop, shared by the pipelined and the
    dedicated decoupled topologies (one copy of the truncation-bootstrap /
    episode-stats / buffer-layout logic).  Returns
    ``(last_obs, numpy_rollout, key, policy_steps_taken)``; callers marshal
    the numpy stacks to their own device/mesh layout.  ``fold_rank`` keeps
    per-rank action sampling decorrelated where the base key stream must
    stay rank-identical (the pipelined multi-process path)."""
    envs, rb, aggregator = ctx["envs"], ctx["rb"], ctx["aggregator"]
    policy_step_fn, values_fn = ctx["policy_step_fn"], ctx["values_fn"]
    obs_keys, cnn_keys, mlp_keys = ctx["obs_keys"], ctx["cnn_keys"], ctx["mlp_keys"]
    act_space, gamma = ctx["act_space"], ctx["gamma"]
    steps = 0
    with jax.default_device(ctx["host"]):
        # one fold at entry starts a (rank-decorrelated) player stream that
        # then advances INSIDE policy_step_fn — one dispatch per env step;
        # the base `key` advances once per rollout, rank-identically
        sk = jax.random.fold_in(key, fold_rank if fold_rank is not None else 997)
        key, _ = jax.random.split(key)
        for _ in range(ctx["rollout_steps"]):
            steps += ctx["step_increment"]
            dev_obs = prepare_obs(obs, cnn_keys, mlp_keys)
            actions, logprobs, _, sk = policy_step_fn(p_params, dev_obs, sk)
            actions_np = np.asarray(actions)
            next_obs, rewards, terminated, truncated, info = envs.step(
                actions_for_env(actions_np, act_space)
            )
            dones = np.logical_or(terminated, truncated)
            rewards = np.asarray(rewards, np.float32)
            if np.any(truncated):
                # truncation bootstrap: add gamma*V(s_T) to rewards of
                # truncated envs (reference: sheeprl/algos/ppo/ppo.py:287-306)
                final_obs = final_obs_rows(info, np.nonzero(truncated)[0], obs_keys)
                if final_obs is not None:
                    padded = {kk: np.asarray(next_obs[kk]).copy() for kk in obs_keys}
                    for kk in obs_keys:
                        padded[kk][truncated] = final_obs[kk]
                    vals = np.asarray(values_fn(p_params, prepare_obs(padded, cnn_keys, mlp_keys)))
                    rewards[truncated] += gamma * vals[truncated]
            step_data = {}
            for kk in obs_keys:
                step_data[kk] = np.asarray(obs[kk])[None]
            step_data["actions"] = actions_np[None]
            step_data["logprobs"] = np.asarray(logprobs)[None]
            step_data["rewards"] = rewards[None]
            step_data["dones"] = dones[None].astype(np.float32)
            rb.add({kk: v[..., None] if v.ndim == 2 else v for kk, v in step_data.items()})
            obs = next_obs
            for ep_ret, ep_len in episode_stats(info):
                aggregator.update("Rewards/rew_avg", ep_ret)
                aggregator.update("Game/ep_len_avg", ep_len)
    local = rb.buffer
    rollout = {kk: np.asarray(local[kk]) for kk in obs_keys}
    rollout["actions"] = np.asarray(local["actions"])
    rollout["logprobs"] = np.asarray(local["logprobs"][..., 0])
    rollout["rewards"] = np.asarray(local["rewards"][..., 0])
    rollout["dones"] = np.asarray(local["dones"][..., 0])
    return obs, rollout, key, steps


@register_algorithm(decoupled=True, name="ppo_decoupled")
def main(fabric: Any, cfg: Any) -> None:
    if cfg.buffer.get("share_data", False):
        import warnings

        warnings.warn(
            "buffer.share_data=True is ignored by decoupled PPO: the player "
            "already collects ONE global rollout that every trainer minibatches "
            "(reference: sheeprl/algos/ppo/ppo_decoupled.py:639-643)"
        )
    from sheeprl_tpu.parallel.topology import resolve_topology

    topo_name = resolve_topology(cfg, fabric)
    if topo_name == "pod":
        # the cross-host actor/learner split (docs/distributed.md)
        from sheeprl_tpu.sebulba.pod import run_pod

        run_pod(fabric, cfg)
        return
    if topo_name == "sebulba":
        # the Sebulba actor/learner device split (docs/sebulba.md)
        from sheeprl_tpu.sebulba.ppo import run_sebulba

        run_sebulba(fabric, cfg)
        return
    dedicated = (cfg.algo.get("player", {}) or {}).get("dedicated", False)
    if dedicated and fabric.num_processes > 1:
        # DEPRECATION SHIM: the two-rank (dedicated player process) split is
        # superseded by the single-controller Sebulba device split, which
        # keeps the overlap without shipping rollouts over host collectives
        import warnings

        warnings.warn(
            "algo.player.dedicated=True (the two-rank player/trainer split) "
            "is deprecated: use the Sebulba device split instead "
            "(topology=sebulba topology.actor_devices=K, docs/sebulba.md). "
            "The cross-process path still runs for now.",
            DeprecationWarning,
        )
        return _dedicated_main(fabric, cfg)
    if dedicated:
        import warnings

        warnings.warn(
            "algo.player.dedicated=True needs >= 2 processes (jax.distributed); "
            "falling back to the single-controller pipelined topology "
            "(deprecated — prefer topology=sebulba, docs/sebulba.md)",
            UserWarning,
        )
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = vectorize(
        cfg,
        [
            make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
            for i in range(num_envs)
        ],
    )
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the rollout/train RNG stream bit-exactly (this loop threads
        # one key through collect_rollout; per-rank separation is fold_in'd
        # inside the policy step)
        key = jnp.asarray(state["key"])
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent"))
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    opt_state = fabric.replicate(state.get("opt_state") or optimizer.init(params))

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    # on-policy loops honor algo.player.device (placement only; the sync
    # cadence options are meaningless on-policy: rollouts must use the
    # current weights)
    host = fabric.player_device(cfg)
    gamma = float(cfg.algo.gamma)
    policy_step_fn, values_fn, train_phase, _ = _build_train_fns(
        agent, optimizer, cfg, obs_keys, actions_dim, is_continuous, dist_type
    )

    rollout_steps = int(cfg.algo.rollout_steps)
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * rollout_steps * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    clip_coef_v = float(cfg.algo.clip_coef)
    ent_coef_v = float(cfg.algo.ent_coef)

    rb = ReplayBuffer(rollout_steps, num_envs, memmap=False, obs_keys=obs_keys)

    rollout_ctx = {
        "envs": envs, "rb": rb, "aggregator": aggregator, "host": host,
        "policy_step_fn": policy_step_fn, "values_fn": values_fn,
        "obs_keys": obs_keys, "cnn_keys": cnn_keys, "mlp_keys": mlp_keys,
        "act_space": act_space, "gamma": gamma,
        "rollout_steps": rollout_steps,
        # GLOBAL env-step accounting: every process steps its own envs
        "step_increment": num_envs * fabric.num_processes,
    }

    def collect_rollout(obs, player_params, key):
        """One rollout with the (possibly stale) player params; per-rank
        sampling folds the rank into the player key only (the shared key
        stream must stay rank-identical for the train dispatch)."""
        nonlocal policy_step
        obs, rollout_np, key, steps = _run_rollout(rollout_ctx, obs, player_params, key, fold_rank=rank)
        policy_step += steps
        from sheeprl_tpu.algos.ppo.ppo import _obs_to_device

        rollout = {}
        for k in obs_keys:
            rollout[k] = _obs_to_device(rollout_np[k], k in cnn_keys)
        for k in ("actions", "logprobs", "rewards", "dones"):
            rollout[k] = jnp.asarray(rollout_np[k])
        return obs, rollout, key

    # the train phase is a GLOBAL program: its batch covers all ranks
    sharded_envs, B = fabric.env_sharding_plan(num_envs, "decoupled PPO")
    T = rollout_steps
    global_bs = min(int(cfg.algo.per_rank_batch_size) * fabric.world_size, T * B)
    num_minibatches = -(-T * B // global_bs)

    def ship(rollout, axis=1):
        if sharded_envs:
            return fabric.shard_batch(rollout, axis=axis)
        return fabric.replicate(rollout)

    # ---------------- pipelined main loop -----------------------------------
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    player_params = fabric.to_host(params)
    last_losses = None

    with timer("Time/env_interaction_time"):
        obs, rollout, key = collect_rollout(obs, player_params, key)

    for update in range(start_iter, total_iters + 1):
        # 1. dispatch training for rollout k (async — returns immediately)
        with timer("Time/train_time"):
            key, tk = jax.random.split(key)
            params, opt_state, last_losses = train_phase(
                params, opt_state, ship(rollout),
                ship(prepare_obs(obs, cnn_keys, mlp_keys), axis=0),
                tk, jnp.float32(clip_coef_v), jnp.float32(ent_coef_v),
                batch_size=global_bs, num_minibatches=num_minibatches,
            )
        # 2. collect rollout k+1 with the stale player while the device trains
        if update < total_iters:
            with timer("Time/env_interaction_time"):
                obs, rollout, key = collect_rollout(obs, player_params, key)
        # 3. refresh the player (device is done by now; transfer is the wait)
        player_params = fabric.to_host(params)

        # schedules (reference: ppo_decoupled.py:586-594)
        if cfg.algo.anneal_lr:
            opt_state = set_learning_rate(
                opt_state,
                polynomial_decay(update, initial=float(cfg.algo.optimizer.lr), final=0.0, max_decay_steps=total_iters),
            )
        if cfg.algo.anneal_clip_coef:
            clip_coef_v = polynomial_decay(
                update, initial=float(cfg.algo.clip_coef), final=0.0, max_decay_steps=total_iters
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef_v = polynomial_decay(
                update, initial=float(cfg.algo.ent_coef), final=0.0, max_decay_steps=total_iters
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                pg, vl, ent = last_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", ent)
            last_log = flush_metrics(aggregator, timer, logger, policy_step, last_log)

        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            fabric.call(
                "on_checkpoint_player",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    envs.close()
    ckpt_mgr.finalize()
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()


def _dedicated_main(fabric: Any, cfg: Any) -> None:
    """Cross-process player/trainer split (``algo.player.dedicated=True``,
    requires >= 2 processes).

    Process topology, matching the reference's decoupled PPO
    (reference: sheeprl/algos/ppo/ppo_decoupled.py:32-365 player,
    :368-620 trainer, :623-670 group setup):

    * process 0 — the PLAYER: owns the envs, acts with a host-device policy
      copy, never joins the train mesh;
    * processes 1..N-1 — TRAINERS: own a sub-mesh over their devices (the
      reference's trainer-only DDP ``optimization_pg``) and run the jitted
      train phase, gradients all-reduced by GSPMD over the sub-mesh.

    Per-iteration protocol (reference's scatter/broadcast collectives →
    host object collectives over DCN):

    1. player broadcasts rollout *k* (+ final obs) to everyone  [src=0];
    2. trainers dispatch the train phase on rollout *k* while the player
       collects rollout *k+1* on weights from iteration *k-1* — the
       cross-process overlap the reference gets from its process split;
    3. the first trainer broadcasts refreshed weights (+losses, + full
       train state on checkpoint cadence) [src=1]; the player refreshes
       its policy and logs/saves.
    """
    rank = fabric.global_rank
    is_player = rank == 0
    key = fabric.seed_everything(cfg.seed)
    if is_player:
        # fork the player's key stream off the trainers' (the coupled path's
        # fold_in(rank) separation): without this, the player's action keys
        # at step i would exactly equal the trainers' train-phase keys
        key = jax.random.fold_in(key, 0x9E37)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    # commit-protocol/async saves via the manager; cadence stays the
    # deterministic ckpt_due below, and preemption is NOT polled here — the
    # lockstep player↔trainer message protocol cannot tolerate one rank
    # unilaterally breaking out (a SIGTERM usually reaches only one process)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if is_player:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    envs = None
    if is_player:
        envs = vectorize(
            cfg,
            [
                make_env(cfg, cfg.seed + i, 0, run_name=log_dir, vector_env_idx=i)
                for i in range(num_envs)
            ],
        )
        spaces = (envs.single_observation_space, envs.single_action_space)
    else:
        spaces = None
    # trainers never build envs; they learn the spaces from the player
    obs_space, act_space = fabric.broadcast_object(spaces, src=0)
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")
    gamma = float(cfg.algo.gamma)

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    from sheeprl_tpu.parallel.fabric import get_trainer_fabric

    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    # honor algo.player.device (host by default; 'accelerator' = the player
    # process's own otherwise-idle chip, for big pixel encoders)
    host = fabric.player_device(cfg)
    if is_player:
        # player-only agent: params live on the player device, no mesh involved
        from sheeprl_tpu.parallel.fabric import get_single_device_fabric

        player_fabric = get_single_device_fabric(fabric, device=host)
        agent, params = build_agent(
            player_fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent")
        )
        player_params = fabric.copy_to(params, host)
        trainer_fabric = None
    else:
        trainer_fabric = get_trainer_fabric(fabric, player_process=0)
        agent, params = build_agent(
            trainer_fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent")
        )
        opt_state = trainer_fabric.replicate(state.get("opt_state") or optimizer.init(params))

    policy_step_fn, values_fn, train_phase, _ = _build_train_fns(
        agent, optimizer, cfg, obs_keys, actions_dim, is_continuous, dist_type
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    rollout_steps = int(cfg.algo.rollout_steps)
    policy_steps_per_iter = num_envs * rollout_steps  # only the player steps envs
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    clip_coef_v = float(cfg.algo.clip_coef)
    ent_coef_v = float(cfg.algo.ent_coef)

    # deterministic on every process: both sides agree when a checkpoint is
    # due without an extra message.  The player's own policy_step counter
    # runs one rollout AHEAD of the trainers' (it collects k+1 before sync
    # B of iteration k), so cadence uses the canonical per-iteration step.
    base_step = policy_step

    def canonical_step(update: int) -> int:
        return base_step + (update - start_iter + 1) * policy_steps_per_iter

    def ckpt_due(step: int, update: int) -> bool:
        return (
            cfg.checkpoint.every > 0 and step - last_checkpoint >= cfg.checkpoint.every
        ) or (update == total_iters and cfg.checkpoint.save_last)

    # ---------------- player-side rollout ------------------------------------
    rb = ReplayBuffer(rollout_steps, num_envs, memmap=False, obs_keys=obs_keys) if is_player else None

    if is_player:
        rollout_ctx = {
            "envs": envs, "rb": rb, "aggregator": aggregator, "host": host,
            "policy_step_fn": policy_step_fn, "values_fn": values_fn,
            "obs_keys": obs_keys, "cnn_keys": cnn_keys, "mlp_keys": mlp_keys,
            "act_space": act_space, "gamma": gamma,
            "rollout_steps": rollout_steps,
            "step_increment": num_envs,  # only the player steps envs
        }

    def collect_rollout(obs, p_params, k):
        """One rollout; returns raw numpy stacks (shipped over DCN).  The
        player's key stream is already forked off the trainers' (fold_in at
        seed time), so no per-step rank folding is needed."""
        nonlocal policy_step
        obs, rollout_np, k, steps = _run_rollout(rollout_ctx, obs, p_params, k)
        policy_step += steps
        return obs, rollout_np, k

    # ---------------- trainer-side batch assembly ----------------------------
    if not is_player:
        from sheeprl_tpu.parallel.fabric import host_tree_to_mesh

        tmesh = trainer_fabric.mesh
        t_world = trainer_fabric.world_size
        shard_envs = num_envs % t_world == 0
        global_bs = min(int(cfg.algo.per_rank_batch_size) * t_world, rollout_steps * num_envs)
        num_minibatches = -(-rollout_steps * num_envs // global_bs)

        def to_mesh(tree, axis=1):
            return host_tree_to_mesh(tree, tmesh, axis=axis, shard=shard_envs)

        def device_rollout(rollout_np):
            # numpy-side normalize/layout (NO accelerator round-trip: the
            # mesh landing below is the single upload)
            out = {}
            for kk in obs_keys:
                out[kk] = obs_to_np(rollout_np[kk], kk in cnn_keys, rollout=True)
            for kk in ("actions", "logprobs", "rewards", "dones"):
                out[kk] = np.asarray(rollout_np[kk], np.float32)
            return to_mesh(out, axis=1)

    # ---------------- lockstep protocol --------------------------------------
    acc_train_times: Dict[str, float] = {}
    obs = None
    if is_player:
        obs, _ = envs.reset(seed=cfg.seed)
        with timer("Time/env_interaction_time"):
            obs, rollout_np, key = collect_rollout(obs, player_params, key)
    else:
        rollout_np = None

    for update in range(start_iter, total_iters + 1):
        if is_player:
            payload = (rollout_np, {kk: np.asarray(obs[kk]) for kk in obs_keys})
        else:
            payload = None
        rollout_np, last_obs_np = fabric.broadcast_object(payload, src=0)  # sync A
        if not is_player:
            policy_step += policy_steps_per_iter
            with timer("Time/train_time"):
                key, tk = jax.random.split(key)
                params, opt_state, losses = train_phase(
                    params, opt_state, device_rollout(rollout_np),
                    to_mesh({kk: obs_to_np(last_obs_np[kk], kk in cnn_keys) for kk in obs_keys}, axis=0),
                    tk, jnp.float32(clip_coef_v), jnp.float32(ent_coef_v),
                    batch_size=global_bs, num_minibatches=num_minibatches,
                )
        elif update < total_iters:
            # overlap: the player collects rollout k+1 (stale weights) while
            # the trainers crunch rollout k
            with timer("Time/env_interaction_time"):
                obs, rollout_np, key = collect_rollout(obs, player_params, key)

        # sync B: refreshed weights (+ state on checkpoint cadence) → player
        due = ckpt_due(canonical_step(update), update)
        if rank == 1:
            from sheeprl_tpu.parallel.fabric import fetch_local

            host_params = fetch_local(params)
            host_losses = tuple(float(x) for x in fetch_local(losses))
            extra = fetch_local(opt_state) if due else None
            back = (host_params, host_losses, extra, timer.to_dict(reset=True))
        else:
            back = None
        host_params, host_losses, opt_for_ckpt, train_times = fabric.broadcast_object(back, src=1)
        for tk_, tv_ in (train_times or {}).items():
            acc_train_times[tk_] = acc_train_times.get(tk_, 0.0) + tv_

        # schedules march in lockstep on every process
        if cfg.algo.anneal_lr and not is_player:
            opt_state = set_learning_rate(
                opt_state,
                polynomial_decay(update, initial=float(cfg.algo.optimizer.lr), final=0.0, max_decay_steps=total_iters),
            )
        if cfg.algo.anneal_clip_coef:
            clip_coef_v = polynomial_decay(update, initial=float(cfg.algo.clip_coef), final=0.0, max_decay_steps=total_iters)
        if cfg.algo.anneal_ent_coef:
            ent_coef_v = polynomial_decay(update, initial=float(cfg.algo.ent_coef), final=0.0, max_decay_steps=total_iters)

        if is_player:
            player_params = jax.device_put(host_params, host)
            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
            ):
                pg, vl, ent = host_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", ent)
                last_log = flush_metrics(
                    aggregator, timer, logger, policy_step, last_log,
                    extra_times=dict(acc_train_times),
                )
                acc_train_times.clear()
        if due:
            # every process calls the hook: fabric.save writes on the player
            # (global zero) and barriers everyone; keep_last pruning applies
            last_checkpoint = canonical_step(update)
            fabric.call(
                "on_checkpoint_player",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{last_checkpoint}_0.ckpt"),
                state={
                    "agent": host_params,
                    "opt_state": opt_for_ckpt,
                    "update": update,
                    "policy_step": last_checkpoint,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                },
            )

    ckpt_mgr.finalize()
    if is_player:
        envs.close()
        if cfg.algo.run_test:
            test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
