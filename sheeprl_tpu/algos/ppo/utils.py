"""PPO support utilities (reference: sheeprl/algos/ppo/utils.py:1-121)."""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.utils import merge_framestack

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray],
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
) -> Dict[str, jax.Array]:
    """Host numpy obs → device float arrays.

    Images: uint8 ``(B, H, W, C)`` (or frame-stacked ``(B, S, H, W, C)``,
    merged into channels) → float32 ``/ 255``.  Vectors → float32.
    (reference: sheeprl/algos/ppo/utils.py:prepare_obs)
    """
    out: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        out[k] = jnp.asarray(obs_to_np(obs[k], is_image=True))
    for k in mlp_keys:
        out[k] = jnp.asarray(obs_to_np(obs[k], is_image=False))
    return out


def obs_to_np(x: np.ndarray, is_image: bool, rollout: bool = False) -> np.ndarray:
    """Numpy-side obs normalization/layout — THE single copy of the
    frame-stack-merge + ``/255`` rule (:func:`prepare_obs` and the train
    paths delegate here).  ``rollout`` disambiguates the 5-D case: a rollout
    image batch is ``(T, B, H, W, C)`` (+stack dim → 6-D), a per-step batch
    is ``(B, H, W, C)`` (+stack dim → 5-D) — without the flag a non-stacked
    rollout would be garbled as a stacked step batch."""
    x = np.asarray(x)
    if is_image:
        if rollout:
            if x.ndim == 6:  # (T, B, S, H, W, C) frame stack → channels
                x = merge_framestack(x)
        elif x.ndim == 5:  # (B, S, H, W, C) frame stack → channels
            x = merge_framestack(x)
        return np.asarray(x, np.float32) / 255.0
    return np.asarray(x, np.float32)


def actions_for_env(actions: np.ndarray, action_space: gym.Space) -> np.ndarray:
    """Stored float actions → what the env expects."""
    if isinstance(action_space, gym.spaces.Discrete):
        return actions.astype(np.int64).reshape(-1)
    if isinstance(action_space, gym.spaces.MultiDiscrete):
        return actions.astype(np.int64)
    low = np.asarray(action_space.low, np.float32)
    high = np.asarray(action_space.high, np.float32)
    return np.clip(actions.astype(np.float32), low, high)


def spaces_to_dims(action_space: gym.Space) -> Tuple[Tuple[int, ...], bool]:
    """Action-space → (per-branch dims, is_continuous)."""
    if isinstance(action_space, gym.spaces.Discrete):
        return (int(action_space.n),), False
    if isinstance(action_space, gym.spaces.MultiDiscrete):
        return tuple(int(n) for n in action_space.nvec), False
    if isinstance(action_space, gym.spaces.Box):
        return (int(np.prod(action_space.shape)),), True
    raise ValueError(f"Unsupported action space {type(action_space)}")


def test(agent: Any, params: Any, cfg: Any, log_dir: str, logger: Any = None, greedy: bool = True) -> float:
    """Greedy evaluation episode (reference: sheeprl/algos/ppo/utils.py:test)."""
    from sheeprl_tpu.algos.ppo.agent import sample_actions
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    actions_dim, is_continuous = spaces_to_dims(env.action_space)

    dist_type = cfg.get("distribution", {}).get("type", "auto")

    @jax.jit
    def act(p, o, k):
        out, _ = agent.apply(p, o)
        a, _, _ = sample_actions(out, actions_dim, is_continuous, k, greedy=greedy, dist_type=dist_type)
        return a

    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    done, cum_reward = False, 0.0
    while not done:
        batched = {k: np.asarray(v)[None] for k, v in obs.items()}
        o = prepare_obs(batched, cnn_keys, mlp_keys)
        key, sk = jax.random.split(key)
        action = np.asarray(act(params, o, sk))[0]
        obs, reward, terminated, truncated, _ = env.step(actions_for_env(action[None], env.action_space)[0])
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward


def normalize_obs_keys(cfg: Any, obs_space: gym.spaces.Dict) -> None:
    """Validate configured encoder keys against the env's observation space
    (reference does this check in each algo main)."""
    for group in ("cnn_keys", "mlp_keys"):
        keys = cfg.algo[group].encoder
        missing = [k for k in keys if k not in obs_space.spaces]
        if missing:
            raise ValueError(
                f"Configured {group}.encoder={list(keys)} but {missing} not in "
                f"observation space keys {list(obs_space.spaces)}"
            )
    if not cfg.algo.cnn_keys.encoder and not cfg.algo.mlp_keys.encoder:
        raise ValueError("At least one of algo.cnn_keys.encoder / algo.mlp_keys.encoder must be set")
