"""PPO losses (reference: sheeprl/algos/ppo/loss.py:1-75), pure jittable fns."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    if reduction == "none":
        return x
    raise ValueError(f"Unknown reduction '{reduction}'")


def policy_loss(
    new_logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    clip_coef: jax.Array,
    reduction: str = "mean",
) -> jax.Array:
    ratio = jnp.exp(new_logprobs - old_logprobs)
    surr1 = advantages * ratio
    surr2 = advantages * jnp.clip(ratio, 1.0 - clip_coef, 1.0 + clip_coef)
    return _reduce(-jnp.minimum(surr1, surr2), reduction)


def value_loss(
    new_values: jax.Array,
    old_values: jax.Array,
    returns: jax.Array,
    clip_coef: jax.Array,
    clip_vloss: bool,
    reduction: str = "mean",
) -> jax.Array:
    # scale parity with the reference (reference: sheeprl/algos/ppo/loss.py:45-61):
    # the unclipped branch is a PLAIN mse (no 0.5) honoring `reduction`; the
    # clipped branch is ALWAYS 0.5·mean(max(unclipped, clipped)) — the
    # reference ignores `reduction` there, and users porting reference
    # configs rely on the effective vf_coef scale matching exactly
    if not clip_vloss:
        return _reduce((new_values - returns) ** 2, reduction)
    v_clipped = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    losses = jnp.maximum((new_values - returns) ** 2, (v_clipped - returns) ** 2)
    return 0.5 * losses.mean()


def entropy_loss(entropy: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce(-entropy, reduction)
