"""PPO evaluation entrypoint (reference: sheeprl/algos/ppo/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.utils import spaces_to_dims, test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["ppo", "ppo_decoupled"])
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    env = make_env(cfg, cfg.seed, 0)()
    actions_dim, is_continuous = spaces_to_dims(env.action_space)
    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, env.observation_space, state["agent"]
    )
    env.close()
    test(agent, params, cfg, log_dir, logger)
