"""PPO agent (flax).

Capability parity with the reference agent
(reference: sheeprl/algos/ppo/agent.py:55-369): a MultiEncoder feature
extractor feeding separate actor / critic MLP heads; continuous actions
parameterize a Gaussian (mean + state-independent log-std head output),
discrete and multi-discrete actions parameterize per-branch categoricals.

Where the reference maintains a DDP-wrapped training agent plus a
weight-tied single-device ``PPOPlayer`` (agent.py:352-369), the functional
JAX design needs neither: the same pure ``apply`` serves rollout and train,
and "weight tying" is just passing the same params pytree.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from sheeprl_tpu.models.models import MLP, MultiEncoder
from sheeprl_tpu.utils.distribution import Categorical, Normal, TanhNormal, TruncatedNormal


class PPOAgent(nn.Module):
    actions_dim: Tuple[int, ...]
    is_continuous: bool
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    encoder_cfg: Dict[str, Any]
    actor_cfg: Dict[str, Any]
    critic_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> Tuple[jax.Array, jax.Array]:
        enc = self.encoder_cfg
        features = MultiEncoder(
            cnn_keys=tuple(self.cnn_keys),
            mlp_keys=tuple(self.mlp_keys),
            cnn_channels=(32, 64, 64),
            cnn_features_dim=enc.get("cnn_features_dim"),
            mlp_sizes=(enc.get("dense_units", 64),) * enc.get("mlp_layers", 2),
            mlp_layer_norm=enc.get("layer_norm", False),
            mlp_features_dim=enc.get("mlp_features_dim"),
            activation=enc.get("dense_act", "tanh"),
            dtype=self.dtype,
            name="feature_extractor",
        )(obs)

        actor_out = MLP(
            hidden_sizes=(self.actor_cfg.get("dense_units", 64),) * self.actor_cfg.get("mlp_layers", 2),
            output_dim=sum(self.actions_dim) * (2 if self.is_continuous else 1),
            activation=self.actor_cfg.get("dense_act", "tanh"),
            layer_norm=self.actor_cfg.get("layer_norm", False),
            dtype=self.dtype,
            name="actor",
        )(features)

        value = MLP(
            hidden_sizes=(self.critic_cfg.get("dense_units", 64),) * self.critic_cfg.get("mlp_layers", 2),
            output_dim=1,
            activation=self.critic_cfg.get("dense_act", "tanh"),
            layer_norm=self.critic_cfg.get("layer_norm", False),
            dtype=self.dtype,
            name="critic",
        )(features)
        return actor_out.astype(jnp.float32), value.astype(jnp.float32)


def split_actor_out(
    actor_out: jax.Array, actions_dim: Sequence[int], is_continuous: bool
):
    """Interpret the raw actor head output as distribution parameters."""
    if is_continuous:
        mean, log_std = jnp.split(actor_out, 2, axis=-1)
        return mean, jnp.clip(log_std, -10.0, 2.0)
    sections = []
    start = 0
    for d in actions_dim:
        sections.append(actor_out[..., start:start + d])
        start += d
    return sections


def continuous_dist(mean: jax.Array, log_std: jax.Array, dist_type: str = "auto"):
    """Continuous policy distribution selected by ``cfg.distribution.type``
    (reference surface: configs/exp/ppo.yaml ``distribution.type: auto``):
    auto/normal → independent Gaussian, tanh_normal → squashed Gaussian,
    trunc_normal → Normal truncated to [-1, 1]."""
    std = jnp.exp(log_std)
    if dist_type in ("auto", "normal"):
        return Normal(mean, std, event_dims=1)
    if dist_type == "tanh_normal":
        raise ValueError(
            "tanh_normal needs sample-time log-prob correction and is handled "
            "directly in sample_actions/evaluate_actions, never through "
            "continuous_dist"
        )
    if dist_type == "trunc_normal":
        return TruncatedNormal(jnp.tanh(mean), std, low=-1.0, high=1.0, event_dims=1)
    raise ValueError(f"Unknown distribution type '{dist_type}'")


def sample_actions(
    actor_out: jax.Array,
    actions_dim: Sequence[int],
    is_continuous: bool,
    key: jax.Array,
    greedy: bool = False,
    dist_type: str = "auto",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns ``(actions, log_prob, entropy)``.

    Discrete/multi-discrete actions come back as float indices ``(B, n_branches)``
    (the storage layout the buffers use); continuous as ``(B, act_dim)``.
    """
    if is_continuous:
        mean, log_std = split_actor_out(actor_out, actions_dim, True)
        if dist_type == "tanh_normal":
            d = TanhNormal(mean, jnp.exp(log_std), event_dims=1)
            if greedy:
                action = d.mode()
                lp = jnp.zeros(action.shape[:-1])
            else:
                action, lp = d.sample_and_log_prob(key)
            # entropy of the base Gaussian (squashed entropy has no closed form)
            ent = Normal(mean, jnp.exp(log_std), event_dims=1).entropy()
            return action, lp, ent
        dist = continuous_dist(mean, log_std, dist_type)
        action = dist.mode() if greedy else dist.sample(key)
        return action, dist.log_prob(action), dist.entropy()
    logits = split_actor_out(actor_out, actions_dim, False)
    keys = jax.random.split(key, len(logits))
    acts, lps, ents = [], [], []
    for lg, k in zip(logits, keys):
        d = Categorical(lg)
        a = d.mode() if greedy else d.sample(k)
        acts.append(a)
        lps.append(d.log_prob(a))
        ents.append(d.entropy())
    actions = jnp.stack(acts, axis=-1).astype(jnp.float32)
    return actions, sum(lps), sum(ents)


def evaluate_actions(
    actor_out: jax.Array,
    actions: jax.Array,
    actions_dim: Sequence[int],
    is_continuous: bool,
    dist_type: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Log-prob and entropy of stored rollout actions under current params."""
    if is_continuous:
        mean, log_std = split_actor_out(actor_out, actions_dim, True)
        if dist_type == "tanh_normal":
            from sheeprl_tpu.utils.utils import safeatanh

            base = Normal(mean, jnp.exp(log_std), event_dims=1)
            pre = safeatanh(actions)
            lp = base.log_prob(pre) - jnp.sum(
                jnp.log(1.0 - actions**2 + 1e-6), axis=-1
            )
            return lp, base.entropy()
        dist = continuous_dist(mean, log_std, dist_type)
        return dist.log_prob(actions), dist.entropy()
    logits = split_actor_out(actor_out, actions_dim, False)
    lp = 0.0
    ent = 0.0
    for i, lg in enumerate(logits):
        d = Categorical(lg)
        lp = lp + d.log_prob(actions[..., i])
        ent = ent + d.entropy()
    return lp, ent


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Any,
    obs_space: Any,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[PPOAgent, Any]:
    """Construct the module and (replicated) params, optionally from a
    checkpoint (reference: sheeprl/algos/ppo/agent.py:325-369)."""
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    agent = PPOAgent(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        encoder_cfg=dict(cfg.algo.encoder),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
        dtype=fabric.precision.compute_dtype,
    )
    if agent_state is not None:
        params = agent_state
    else:
        dummy = {}
        for k in cnn_keys:
            shape = obs_space[k].shape
            # frame-stacked images arrive merged into channels
            if len(shape) == 4:
                shape = (*shape[1:3], shape[0] * shape[3])
            dummy[k] = jnp.zeros((1, *shape), jnp.float32)
        for k in mlp_keys:
            dummy[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
        params = agent.init(jax.random.PRNGKey(0), dummy)
    return agent, fabric.replicate(params)
