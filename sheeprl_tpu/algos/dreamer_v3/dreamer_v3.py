"""DreamerV3 — world-model RL, the TPU-critical path (SURVEY.md §3.3, §7.6).

Capability parity with the reference train script
(reference: sheeprl/algos/dreamer_v3/dreamer_v3.py:48-780): RSSM world model
with balanced-KL reconstruction training, imagination-based actor/critic
with two-hot returns, percentile return normalization (Moments), target
critic EMA (τ=0.02), Ratio-governed replay, sequential replay with per-env
streams, episode bookkeeping with reset rows, learning-starts prefill.

TPU-native architecture:
* the RSSM sequence loop and the imagination horizon are ``lax.scan``s
  (the reference runs Python loops over time, dreamer_v3.py:115-145/235-241);
* ALL gradient steps of a ratio window run in ONE jitted dispatch: the
  host samples a ``(U, L, B, *)`` block in one call (the reference's own
  bulk-sample pattern, dreamer_v3.py:664-671) and the device scans over U
  full updates (world model + actor + critic + EMA);
* the environment player is a latent-state policy on ``algo.player.device``
  (host CPU by default — zero device round-trips during interaction —
  or ``accelerator`` for thin links / big encoders), refreshed once per
  ratio window via a packed single-transfer param pull;
* replay lives ON DEVICE (``buffer.device``, data/device_replay.py): the
  whole ring — pixels included — is a mesh-sharded HBM pytree, and
  sequence sampling compiles INTO the update dispatch, so steady-state
  training performs zero H2D (supersedes the retired pixel-only
  ``DeviceMirror``); on the host fallback images ship uint8 and normalize
  on device; batches shard over the mesh ``data`` axis, params replicated
  (GSPMD gradient all-reduce), and the Moments quantile is computed on
  the global batch — which IS the reference's all-gathered Moments
  semantics (utils.py:56-63).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import WorldModel, build_agent
from sheeprl_tpu.algos.dreamer_v3.loss import world_model_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    compute_lambda_values,
    moments_update,
    normalize_obs_block,
    prepare_obs,
    test,
)
from sheeprl_tpu.algos.ppo.utils import actions_for_env, spaces_to_dims
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_replay import (
    DeviceReplay,
    HostSpill,
    estimate_step_bytes,
    fit_hbm_window,
    fused_sequence_train,
    resolve_device_replay,
    steady_guard,
    update_chunks,
)
from sheeprl_tpu.parallel.fabric import PlayerSync
from sheeprl_tpu.parallel.pipeline import (
    chunked_rows,
    merge_microbatches,
    pipeline_value_and_grad,
    register_pipeline_metrics,
    resolve_pipeline,
    split_microbatches,
    stage_batch_constraint,
)
from sheeprl_tpu.utils.distribution import (
    Bernoulli,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    Ratio,
    merge_framestack,
    save_configs,
    window_scan,
)


def build_dv3_optimizers(fabric, cfg, params, saved_opt_state=None):
    """Optimizers + (replicated) opt state for the three param groups —
    shared by main(), bench.py and __graft_entry__.py so the benchmarked
    program is the training program."""
    wm_opt = build_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    # shard_params, not replicate: under TP the optimizer moments share the
    # kernels' shapes, so the same column-sharding rule places them
    # consistently with their params (no-op on a pure-data mesh)
    opt_state = fabric.shard_params(
        saved_opt_state
        or {
            "world_model": wm_opt.init(params["world_model"]),
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
        }
    )
    return wm_opt, actor_opt, critic_opt, opt_state


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    dreamer_family_loop(fabric, cfg, build_agent, make_train_phase)


def dreamer_family_loop(
    fabric: Any,
    cfg: Any,
    build_agent_fn: Any,
    make_train_phase_fn: Any,
    optimizer_builder: Any = None,
    initial_state: Any = None,
) -> None:
    """Shared env/replay/dispatch loop of the Dreamer family (V1/V2/V3 and
    the P2E variants differ in modules and losses, not in this loop —
    mirroring how the reference keeps per-version mains structurally
    identical)."""
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    # pipeline parallelism is wired through the dreamer_v3 train-phase
    # builder only: fail HERE (build time, clear message) for the other
    # family members, and surface the schedule shape as Pipeline/* metrics
    pipe = resolve_pipeline(cfg)
    pipe.check_algo(cfg.algo.name)
    if pipe.enabled:
        register_pipeline_metrics(pipe)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    # ---------------- environments (restart-wrapped like the reference,
    # dreamer_v3.py:385-400) --------------------------------------------------
    num_envs = cfg.env.num_envs
    envs = vectorize(
        cfg,
        [
            make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
            for i in range(num_envs)
        ],
    )
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    actions_dim, is_continuous = spaces_to_dims(act_space)
    act_width = int(sum(actions_dim))
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    # ---------------- agent / optimizers ------------------------------------
    state: Dict[str, Any] = dict(initial_state or {})
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    world_model, actor, critic, params = build_agent_fn(
        fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent")
    )
    WM = type(world_model)
    builder = optimizer_builder or build_dv3_optimizers
    wm_opt, actor_opt, critic_opt, opt_state = builder(
        fabric, cfg, params, state.get("opt_state")
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    psync = PlayerSync(
        fabric, cfg, extract=lambda p: {"world_model": p["world_model"], "actor": p["actor"]}
    )
    host = psync.device  # single resolution of algo.player.device
    stoch_flat = world_model.stoch_flat
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    # ---------------- host player --------------------------------------------
    # MineDojo-style action masking: the mask observations (exposed as mlp
    # keys) constrain the player's sampling (reference: MinedojoActor)
    use_action_masks = bool(cfg.algo.actor.get("action_masks", False))
    mask_keys = ("mask_action_type", "mask_craft_smelt", "mask_equip_place", "mask_destroy")

    def player_step_fn(p, carry, obs, k, greedy=False):
        """(h, z, prev_action) carry; returns new carry + env-space action +
        the advanced key (advancing it in-program saves two host dispatches
        per env step)."""
        h, z, prev_a = carry
        k_repr, k_act, k_next = jax.random.split(k, 3)
        embed = world_model.apply(p["world_model"], obs, method=WM.encode)
        is_first = jnp.zeros((h.shape[0], 1))
        h, z, _, _ = world_model.apply(
            p["world_model"], h, z, prev_a, embed, is_first, k_repr, method=WM.dynamic
        )
        latent = jnp.concatenate([z, h], -1)
        head = actor.apply(p["actor"], latent)
        if use_action_masks:
            action = actor.sample_masked(
                head, k_act, {mk: obs[mk] for mk in mask_keys}, greedy=greedy
            )
        else:
            action = actor.sample(head, k_act, greedy=greedy)
        return (h, z, action), action, k_next

    # compile-once routing: the player executable is AOT-compiled per
    # abstract signature and counted by the recompile detector
    player_step = fabric.compile(
        player_step_fn,
        name=f"{cfg.algo.name}.player_step",
        static_argnames=("greedy",),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    def init_player_carry(batch: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return (
            np.zeros((batch, rec_size), np.float32),
            np.zeros((batch, stoch_flat), np.float32),
            np.zeros((batch, act_width), np.float32),
        )

    player_params = psync.init(params)
    player_carry = init_player_carry(num_envs)

    def player_test_step(p, carry, obs, k, greedy):
        if carry is None:
            carry = tuple(jnp.zeros_like(jnp.asarray(c[:1])) for c in init_player_carry(1))
        carry, action, _ = player_step(p, carry, obs, k, greedy=greedy)
        a = np.asarray(action)
        if not is_continuous:
            # one-hot branches → index per branch
            idx, start = [], 0
            for d in actions_dim:
                idx.append(a[..., start:start + d].argmax(-1))
                start += d
            a = np.stack(idx, axis=-1).astype(np.float32)
        return carry, a

    # ---------------- single-dispatch multi-update train phase ---------------
    train_phase = make_train_phase_fn(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=cnn_keys, mlp_keys=mlp_keys, is_continuous=is_continuous,
        params=params, opt_state=opt_state,
    )
    # training-health sentinels (resilience/health.py): wrap the compiled
    # phase (it inlines under the guard's trace) with the non-finite guard +
    # divergence detector, threading the tiny device HealthState first.
    # Covers every dreamer-family entry point — the p2e builders need no
    # changes.  health.enabled=false keeps the exact unguarded program.
    from sheeprl_tpu.resilience.health import DivergenceError, HealthSentinel

    sentinel = HealthSentinel.from_config(cfg, fabric)
    if sentinel is not None:
        sentinel.register()
        train_phase = fabric.compile(
            sentinel.wrap(train_phase),
            name=f"{cfg.algo.name}.train_phase_guarded",
            donate_argnums=(0, 1, 2),
            max_recompiles=cfg.algo.get("max_recompiles"),
        )

    # ---------------- replay buffer ------------------------------------------
    seq_len = int(cfg.algo.per_rank_sequence_length)
    batch_size = int(cfg.algo.per_rank_batch_size) * fabric.local_world_size
    if cfg.buffer.get("type", "sequential") == "episode":
        rb = EpisodeBuffer(
            max(int(cfg.buffer.size), seq_len * 4),
            sequence_length=seq_len,
            n_envs=num_envs,
            prioritize_ends=bool(cfg.buffer.get("prioritize_ends", False)),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}")
            if cfg.buffer.memmap
            else None,
        )
    else:
        capacity = max(int(cfg.buffer.size) // num_envs, seq_len * 2)
        memmap_dir = (
            os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None
        )
        # device-resident replay (data/device_replay.py): the WHOLE ring —
        # pixels included — lives in HBM sharded over the mesh `data` axis,
        # and sequence sampling compiles into the update dispatch.  This
        # subsumes the retired per-device DeviceMirror (pixel-only,
        # probe-gated) and the H2D window_chunks byte budget: in steady
        # state nothing ships per update.  The EpisodeBuffer layout (no
        # ring) and CPU runs keep the host-numpy path.
        if resolve_device_replay(cfg, fabric.accelerator):
            step_bytes = estimate_step_bytes(obs_space, obs_keys, extra_bytes=4 * (act_width + 4))
            hbm_window, spill_needed = fit_hbm_window(
                capacity, num_envs, step_bytes, cfg.buffer.get("hbm_window")
            )
            spill = (
                HostSpill(capacity, num_envs, sequential=True, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
                if spill_needed
                else None
            )
            rb = DeviceReplay(
                hbm_window, num_envs, mesh=fabric.mesh, data_axis=fabric.data_axis, spill=spill
            )
        else:
            rb = EnvIndependentReplayBuffer(
                capacity,
                n_envs=num_envs,
                buffer_cls=SequentialReplayBuffer,
                memmap=cfg.buffer.memmap,
                memmap_dir=memmap_dir,
            )
    use_device_replay = isinstance(rb, DeviceReplay)
    # fold on-device sequence sampling + block prep INTO the compiled update
    # (data/device_replay.fused_sequence_train): the (U, L, B, *) block is
    # gathered from the HBM ring inside the dispatch — the layout/uint8
    # normalization contract of the host path is reproduced by _prep_blocks
    train_phase_dev = None
    if use_device_replay:
        def _prep_blocks(b):
            out = {}
            for kk in cnn_keys:
                x = b[kk]
                if x.ndim == 7:  # (U, L, B, S, H, W, C) framestack
                    x = merge_framestack(x, jnp)
                out[kk] = x  # uint8 rides to the train phase; /255 on device
            for kk in mlp_keys:
                x = b[kk].astype(jnp.float32)
                out[kk] = x.reshape(*x.shape[:3], -1)
            out["actions"] = b["actions"].astype(jnp.float32)
            for kk in ("rewards", "terminated", "is_first"):
                out[kk] = b[kk][..., 0].astype(jnp.float32)
            return out

        train_phase_dev = fused_sequence_train(
            fabric,
            train_phase,
            rb,
            batch_size,
            seq_len,
            _prep_blocks,
            name=f"{cfg.algo.name}.train_phase_device",
            max_recompiles=cfg.algo.get("max_recompiles"),
            health=sentinel is not None,
        )
    guard_on = bool(cfg.buffer.get("transfer_guard", False)) and use_device_replay
    # a checkpoint only contains "rb" if it was saved with buffer.checkpoint
    # (or injected explicitly, e.g. P2E finetuning's load_from_exploration) —
    # so presence alone decides
    if state and state.get("rb") is not None:
        rb.load_state_dict({"buffers": state["rb"]}) if isinstance(state["rb"], list) else rb.load_state_dict(state["rb"])

    # ---------------- counters ------------------------------------------------
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * int(cfg.env.action_repeat) * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        # dry run = collect just enough for one sequence sample (2x for the
        # EpisodeBuffer, which must first COMMIT a >=seq_len episode), then
        # ONE optimization dispatch
        total_iters = 2 * int(cfg.algo.per_rank_sequence_length) + 4
    learning_starts = int(cfg.algo.learning_starts) // policy_steps_per_iter if not cfg.dry_run else 0
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    if state:
        learning_starts += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])
    if state and "psync" in state:
        psync.load_state_dict(state["psync"])

    # ---------------- env bookkeeping (reference: dreamer_v3.py:540-657) ----
    # rank-offset: each process's envs must be distinct streams or
    # multi-host DP collects the same data num_processes times
    obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
    step_data: Dict[str, np.ndarray] = {}
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[None]
    step_data["rewards"] = np.zeros((1, num_envs), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs), np.float32)
    step_data["is_first"] = np.ones((1, num_envs), np.float32)
    last_metrics = None
    counter_dev = None  # device-resident grad-step counter (zero-copy path)
    h_dev = None  # device-resident sentinel state (resilience/health.py)
    train_windows = 0  # completed dispatched windows (guards arm past warmup)
    # per-rank player key stream, advanced inside player_step; the main
    # `key` stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    # parallel compile warm-up: the player executable lowers+compiles in the
    # pool while this thread steps random prefill actions (XLA compilation
    # releases the GIL), so the first post-prefill policy step finds its
    # executable already built instead of stalling the rollout
    if bool(cfg.algo.get("compile_warmup", True)):
        def _warm_player(first_obs=obs):
            with jax.default_device(host):
                warm_obs = prepare_obs(first_obs, cnn_keys, mlp_keys)
                carry0 = tuple(jnp.asarray(c) for c in init_player_carry(num_envs))
                player_step.warmup(player_params, carry0, warm_obs, player_key)

        fabric.compile_pool.submit_fn(_warm_player)

    from sheeprl_tpu.utils.profiler import ProfilerGate

    profiler = ProfilerGate(cfg, log_dir)
    for update in range(start_iter, total_iters + 1):
        profiler.step(update)
        policy_step += policy_steps_per_iter
        with timer("Time/env_interaction_time"):
            if update <= learning_starts and not state:
                sampled = np.stack([act_space.sample() for _ in range(num_envs)])
                env_actions = np.asarray(sampled, np.float32).reshape(num_envs, -1)
                if is_continuous:
                    actions = env_actions
                else:
                    idx = sampled.reshape(num_envs, -1)
                    parts = []
                    for b, d in enumerate(actions_dim):
                        oh = np.zeros((num_envs, d), np.float32)
                        oh[np.arange(num_envs), idx[:, b]] = 1.0
                        parts.append(oh)
                    actions = np.concatenate(parts, -1)
            else:
                with jax.default_device(host):
                    dev_obs = prepare_obs(obs, cnn_keys, mlp_keys)
                    new_carry, action_oh, player_key = player_step(
                        player_params,
                        tuple(jnp.asarray(c) for c in player_carry),
                        dev_obs,
                        player_key,
                    )
                    player_carry = tuple(np.array(c) for c in new_carry)
                    actions = np.asarray(action_oh, np.float32)
                if is_continuous:
                    env_actions = actions
                else:
                    idxs, start = [], 0
                    for d in actions_dim:
                        idxs.append(actions[:, start:start + d].argmax(-1))
                        start += d
                    env_actions = np.stack(idxs, -1).astype(np.float32)

            step_data["actions"] = actions[None]
            rb.add({k: (v[..., None] if v.ndim == 2 else v) for k, v in step_data.items()})

            next_obs, rewards, terminated, truncated, info = envs.step(
                actions_for_env(env_actions, act_space)
            )
            dones = np.logical_or(terminated, truncated)

            step_data["is_first"] = np.zeros((1, num_envs), np.float32)

            # env crashed + restarted: the stream broke — mark the last stored
            # step truncated and restart the episode bookkeeping
            # (reference: dreamer_v3.py:595-608)
            roe = info.get("restart_on_exception")
            if roe is not None:
                for i in np.nonzero(np.asarray(roe, bool))[0]:
                    if dones[i]:
                        continue
                    # the stream broke: the next stored step starts a new
                    # episode, and the buffer truncates (or drops) the
                    # partial one — see ReplayBuffer/EpisodeBuffer.repair_tail
                    step_data["is_first"][:, i] = 1.0
                    rb.repair_tail(i)

            for ep_ret, ep_len in episode_stats(info):
                aggregator.update("Rewards/rew_avg", ep_ret)
                aggregator.update("Game/ep_len_avg", ep_len)

            # real final observation of done envs
            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            done_idx = np.nonzero(dones)[0]
            if done_idx.size:
                final = final_obs_rows(info, done_idx, obs_keys)
                if final is not None:
                    for k in obs_keys:
                        real_next_obs[k][done_idx] = final[k]

            for k in obs_keys:
                step_data[k] = np.asarray(next_obs[k])[None]
            obs = next_obs
            rewards = np.asarray(rewards, np.float32)
            if cfg.env.clip_rewards:
                rewards = np.tanh(rewards)
            step_data["rewards"] = rewards[None]
            step_data["terminated"] = terminated.astype(np.float32)[None]
            step_data["truncated"] = truncated.astype(np.float32)[None]

            if done_idx.size:
                # store the final transition row for finished episodes
                # (reference: dreamer_v3.py:639-657)
                reset_data: Dict[str, np.ndarray] = {}
                for k in obs_keys:
                    reset_data[k] = real_next_obs[k][done_idx][None]
                reset_data["terminated"] = step_data["terminated"][:, done_idx, None]
                reset_data["truncated"] = step_data["truncated"][:, done_idx, None]
                reset_data["actions"] = np.zeros((1, done_idx.size, act_width), np.float32)
                reset_data["rewards"] = step_data["rewards"][:, done_idx, None]
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                rb.add(reset_data, indices=done_idx.tolist())

                step_data["rewards"][:, done_idx] = 0.0
                step_data["terminated"][:, done_idx] = 0.0
                step_data["truncated"][:, done_idx] = 0.0
                step_data["is_first"][:, done_idx] = 1.0
                fresh = init_player_carry(done_idx.size)
                for c_old, c_new in zip(player_carry, fresh):
                    c_old[done_idx] = c_new

        # ---------------- training -------------------------------------------
        if isinstance(rb, EpisodeBuffer):
            can_sample = len(rb) > seq_len and len(rb.buffer) > 0
        elif use_device_replay:
            can_sample = rb.can_sample_sequences(seq_len)
        else:
            can_sample = any(len(b) > seq_len for b in rb.buffer)
        if update >= learning_starts and can_sample:
            per_rank_gradient_steps = ratio(policy_step / fabric.world_size)
            if cfg.dry_run:
                per_rank_gradient_steps = 1 if update == total_iters else 0
            if per_rank_gradient_steps > 0 and train_phase_dev is not None:
                with timer("Time/train_time"):
                    # zero-copy steady state: sequences are sampled from the
                    # HBM ring INSIDE the compiled dispatch — nothing ships
                    # H2D per update, and (optionally) the transfer guard
                    # proves it past the first (warmup) window.  Windows are
                    # still chunked into powers of two: distinct U values are
                    # distinct executables, so bursts must reuse shapes
                    # (data/device_replay.update_chunks).
                    if counter_dev is None:
                        # replicated on the mesh, matching the program's output
                        # placement — a single-device stage would cost one
                        # extra (first-window) executable on multi-device
                        counter_dev = fabric.replicate(np.int32(grad_step_counter))
                    if sentinel is not None and h_dev is None:
                        h_dev = sentinel.init_state()
                    player_params = psync.before_dispatch(player_params)
                    with steady_guard(guard_on and train_windows > 0):
                        # chunk cap honors BOTH budgets: compile reuse and the
                        # HBM bytes the gathered (U, L, B, *) block materializes
                        for u in update_chunks(
                            per_rank_gradient_steps,
                            bytes_per_update=rb.sampled_bytes_per_update(batch_size, seq_len),
                        ):
                            key, tk = jax.random.split(key)
                            if sentinel is not None:
                                params, opt_state, h_dev, counter_dev, last_metrics = (
                                    train_phase_dev(
                                        params, opt_state, h_dev, rb.buffers, rb.cursor,
                                        tk, counter_dev, n_samples=u,
                                    )
                                )
                            else:
                                params, opt_state, counter_dev, last_metrics = train_phase_dev(
                                    params, opt_state, rb.buffers, rb.cursor, tk,
                                    counter_dev, n_samples=u,
                                )
                            grad_step_counter += u
                    train_windows += 1
                    player_params = psync.after_dispatch(params, player_params)
            elif per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    # host-numpy fallback (CPU runs, EpisodeBuffer): burst
                    # windows (the first one repays every pre-training env
                    # step at once) are chunked into powers of two so a burst
                    # reuses a handful of compiled window shapes.
                    #
                    # ONE player sync per ratio window, hoisted OUT of the
                    # chunk loop: a per-chunk refresh would pull the full
                    # player params D2H once per chunk (~6 s per pull over
                    # the tunnel x 257 burst chunks stalled the r5 capture)
                    player_params = psync.before_dispatch(player_params)
                    for u in update_chunks(per_rank_gradient_steps):
                        sample = rb.sample(
                            batch_size,
                            n_samples=u,
                            sequence_length=seq_len,
                        )  # (U, L, batch, *)
                        blocks: Dict[str, jax.Array] = {}
                        for k in cnn_keys:
                            x = np.asarray(sample[k])
                            if x.ndim == 7:  # (U, L, B, S, H, W, C) framestack
                                x = merge_framestack(x)
                            # ship uint8 (4x less H2D traffic); the train phase
                            # normalizes on device
                            blocks[k] = jnp.asarray(x)
                        for k in mlp_keys:
                            x = np.asarray(sample[k], np.float32)
                            blocks[k] = jnp.asarray(x.reshape(*x.shape[:3], -1))
                        blocks["actions"] = jnp.asarray(np.asarray(sample["actions"], np.float32))
                        blocks["rewards"] = jnp.asarray(np.asarray(sample["rewards"], np.float32)[..., 0])
                        blocks["terminated"] = jnp.asarray(np.asarray(sample["terminated"], np.float32)[..., 0])
                        blocks["is_first"] = jnp.asarray(np.asarray(sample["is_first"], np.float32)[..., 0])
                        blocks = fabric.shard_batch(blocks, axis=2)
                        key, tk = jax.random.split(key)
                        if sentinel is not None:
                            if h_dev is None:
                                h_dev = sentinel.init_state()
                            h_dev, params, opt_state, last_metrics = train_phase(
                                h_dev, params, opt_state, blocks, tk,
                                jnp.int32(grad_step_counter),
                            )
                        else:
                            params, opt_state, last_metrics = train_phase(
                                params, opt_state, blocks, tk, jnp.int32(grad_step_counter)
                            )
                        grad_step_counter += u
                    player_params = psync.after_dispatch(params, player_params)

        # ---------------- training-health sentinel -----------------------------
        # per-interval host poll of the device HealthState: Health/* metrics
        # through the hub + recorder events.  The dreamer loops implement
        # rollback through the process boundary: the typed DivergenceError
        # reaches cli.run's crash path (postmortem reason surfaced) and the
        # supervisor relaunches with checkpoint.resume_from=auto — i.e.
        # rollback to the last committed snapshot.
        if (
            sentinel is not None
            and h_dev is not None
            and sentinel.should_poll(update, total_iters)
            and sentinel.poll(h_dev, policy_step) == "rollback"
        ):
            raise DivergenceError(
                f"training diverged at step {policy_step}; relaunch with "
                "checkpoint.resume_from=auto to roll back to the last committed "
                "snapshot (sheeprl-tpu-supervise does this automatically)"
            )

        # ---------------- logging ---------------------------------------------
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_metrics is not None:
                wm_l, ol, rl, sl, cl, kl_, pl, vl, pe, pre = last_metrics
                aggregator.update("Loss/world_model_loss", wm_l)
                aggregator.update("Loss/observation_loss", ol)
                aggregator.update("Loss/reward_loss", rl)
                aggregator.update("Loss/state_loss", sl)
                aggregator.update("Loss/continue_loss", cl)
                aggregator.update("State/kl", kl_)
                aggregator.update("Loss/policy_loss", pl)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("State/post_entropy", pe)
                aggregator.update("State/prior_entropy", pre)
            last_log = flush_metrics(
                aggregator, timer, logger, policy_step, last_log,
                extra_metrics={
                    "Params/replay_ratio": grad_step_counter * fabric.world_size / max(policy_step, 1),
                    # deferred-sync staleness, made visible (ISSUE 12)
                    **psync.metrics(),
                },
            )

        # ---------------- checkpoint ------------------------------------------
        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "psync": psync.state_dict(),
                "grad_steps": grad_step_counter,
            }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    profiler.close()
    envs.close()
    if sentinel is not None:
        sentinel.close()
    if getattr(rb, "spill", None) is not None:
        rb.spill.close()
    ckpt_mgr.finalize()
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        # the deferred-sync player may be one window stale: sync once more
        player_params = psync.init(params)
        test(player_test_step, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()


def make_wm_stages(cfg, world_model, cnn_keys, mlp_keys):
    """Build the world-model forward and its pipeline stage chain.

    Returns ``(wm_forward, stage_fns, stage_names)``.  Module-level (not
    nested in :func:`make_train_phase`) so ``bench.py --mode pipeline``
    can compile standalone per-stage programs
    (``parallel/pipeline.py compile_stage_pair``) from exactly the
    functions the fused train phase pipelines.
    """
    obs_keys = tuple(cnn_keys) + tuple(mlp_keys)
    stoch_flat = world_model.stoch_flat
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    wm_loss_cfg = dict(
        kl_dynamic=float(cfg.algo.world_model.kl_dynamic),
        kl_representation=float(cfg.algo.world_model.kl_representation),
        kl_free_nats=float(cfg.algo.world_model.kl_free_nats),
        kl_regularizer=float(cfg.algo.world_model.kl_regularizer),
        continue_scale_factor=float(cfg.algo.world_model.continue_scale_factor),
    )
    remat = bool(cfg.algo.get("remat", False))

    def maybe_remat(f):
        return jax.checkpoint(f) if remat else f

    pipe = resolve_pipeline(cfg)

    # The world-model forward is factored into its pipeline stage map
    # (encoder → RSSM → heads/decoder, parallel/pipeline.py): ``_encode``,
    # ``_rssm_inputs`` and ``_heads_losses`` are shared verbatim by the
    # monolithic ``wm_forward`` (pipeline off — op-for-op the pre-pipeline
    # program) and by the per-microbatch stage functions (pipeline on).  The
    # ONLY computation the two paths do differently is where posterior
    # sampling noise is drawn: ``wm_forward`` samples inside the scan at
    # batch shape (``WorldModel.dynamic``), the stages consume pre-drawn
    # full-batch noise row-sliced per microbatch
    # (``WorldModel.dynamic_noise`` — the sample-invariance law, so both
    # paths draw bit-identical posterior samples).

    def _encode(wm_params, data):
        """Stage 1 — normalize + encode: → (obs, embed (L, B, E))."""
        L, B = data["rewards"].shape
        obs = normalize_obs_block(data, cnn_keys, obs_keys)
        flat_obs = {kk: v.reshape((L * B,) + v.shape[2:]) for kk, v in obs.items()}
        embed = world_model.apply(wm_params, flat_obs, method=WorldModel.encode)
        return obs, embed.reshape(L, B, -1)

    def _rssm_inputs(data):
        # shifted actions: h_t consumes a_{t-1} (reference: dreamer_v3.py:105)
        actions = jnp.concatenate(
            [jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], axis=0
        )
        is_first = data["is_first"].at[0].set(1.0)[..., None]
        return actions, is_first

    def _heads_losses(wm_params, data, obs, latents, post_logits, prior_logits):
        """Stage 3 — decoder/reward/continue heads + world-model loss."""
        L, B = data["rewards"].shape
        flat_latents = latents.reshape(L * B, -1)

        recon = world_model.apply(wm_params, flat_latents, method=WorldModel.decode)
        obs_log_probs = {}
        for kk in cnn_keys:
            dist = MSEDistribution(recon[kk].reshape(obs[kk].shape), event_dims=3)
            obs_log_probs[kk] = dist.log_prob(obs[kk])
        for kk in mlp_keys:
            dist = SymlogDistribution(recon[kk].reshape(L, B, -1), event_dims=1)
            obs_log_probs[kk] = dist.log_prob(obs[kk])

        reward_logits = world_model.apply(wm_params, flat_latents, method=WorldModel.reward_logits)
        pr = TwoHotEncodingDistribution(reward_logits.reshape(L, B, -1), dims=1)
        reward_lp = pr.log_prob(data["rewards"][..., None])

        cont_logits = world_model.apply(wm_params, flat_latents, method=WorldModel.continue_logits)
        pc = Bernoulli(cont_logits.reshape(L, B), event_dims=0)
        cont_lp = pc.log_prob(1.0 - data["terminated"])

        loss, aux = world_model_loss(
            obs_log_probs, reward_lp, cont_lp, post_logits, prior_logits, **wm_loss_cfg
        )
        aux["latents"] = latents
        aux["post_logits"] = post_logits
        aux["prior_logits"] = prior_logits
        return loss, aux

    def wm_forward(wm_params, data, k):
        """Encoder + RSSM scan + heads → loss and latents for behavior."""
        L, B = data["rewards"].shape
        obs, embed = _encode(wm_params, data)
        actions, is_first = _rssm_inputs(data)

        h0 = jnp.zeros((B, rec_size))
        z0 = jnp.zeros((B, stoch_flat))

        keys = jax.random.split(k, L)
        if world_model.decoupled_rssm:
            # DecoupledRSSM: ALL posteriors computed and sampled in one
            # batched pass (no h dependence); only the GRU+prior stay in the
            # scan — a much lighter sequential step on TPU
            post_logits = world_model.apply(
                wm_params, embed.reshape(L * B, -1), method=WorldModel.posterior_decoupled
            ).reshape(L, B, world_model.stochastic_size, world_model.discrete_size)
            zs = jax.vmap(
                lambda lg, kk: OneHotCategorical(lg, unimix=world_model.unimix).rsample(kk)
            )(post_logits, keys).reshape(L, B, stoch_flat)
            prev_zs = jnp.concatenate([jnp.zeros_like(zs[:1]), zs[:-1]], 0)

            def step(h, xs):
                prev_z, act_t, first_t = xs
                h, prior_logits = world_model.apply(
                    wm_params, h, prev_z, act_t, first_t, method=WorldModel.recurrent_prior
                )
                return h, (h, prior_logits)

            _, (hs, prior_logits) = jax.lax.scan(maybe_remat(step), h0, (prev_zs, actions, is_first))
        else:
            def step(carry, xs):
                h, z = carry
                embed_t, act_t, first_t, k_t = xs
                h, z, post_logits, prior_logits = world_model.apply(
                    wm_params, h, z, act_t, embed_t, first_t, k_t, method=WorldModel.dynamic
                )
                return (h, z), (h, z, post_logits, prior_logits)

            _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(
                maybe_remat(step), (h0, z0), (embed, actions, is_first, keys)
            )
        latents = jnp.concatenate([zs, hs], -1)  # (L, B, stoch+rec)
        return _heads_losses(wm_params, data, obs, latents, post_logits, prior_logits)

    # ---- pipeline stage functions (parallel/pipeline.py chain shapes) ----
    # const per microbatch: {"data": dict of (L, b, *), "noise": (L, b, S, D)}

    def _enc_stage(wm_params, _carry, const):
        _, embed = _encode(wm_params, const["data"])
        return embed

    def _rssm_stage(wm_params, embed, const):
        data, noise = const["data"], const["noise"]
        L, B = data["rewards"].shape
        actions, is_first = _rssm_inputs(data)
        h0 = jnp.zeros((B, rec_size))
        z0 = jnp.zeros((B, stoch_flat))
        if world_model.decoupled_rssm:
            post_logits = world_model.apply(
                wm_params, embed.reshape(L * B, -1), method=WorldModel.posterior_decoupled
            ).reshape(L, B, world_model.stochastic_size, world_model.discrete_size)
            zs = jax.vmap(
                lambda lg, nz: OneHotCategorical(
                    lg, unimix=world_model.unimix
                ).rsample_from_noise(nz)
            )(post_logits, noise).reshape(L, B, stoch_flat)
            prev_zs = jnp.concatenate([jnp.zeros_like(zs[:1]), zs[:-1]], 0)

            def step(h, xs):
                prev_z, act_t, first_t = xs
                h, prior_logits = world_model.apply(
                    wm_params, h, prev_z, act_t, first_t, method=WorldModel.recurrent_prior
                )
                return h, (h, prior_logits)

            _, (hs, prior_logits) = jax.lax.scan(maybe_remat(step), h0, (prev_zs, actions, is_first))
        else:
            def step(carry, xs):
                h, z = carry
                embed_t, act_t, first_t, nz_t = xs
                h, z, post_logits, prior_logits = world_model.apply(
                    wm_params, h, z, act_t, embed_t, first_t, nz_t,
                    method=WorldModel.dynamic_noise,
                )
                return (h, z), (h, z, post_logits, prior_logits)

            _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(
                maybe_remat(step), (h0, z0), (embed, actions, is_first, noise)
            )
        latents = jnp.concatenate([zs, hs], -1)
        return latents, post_logits, prior_logits

    def _heads_stage(wm_params, carry, const):
        latents, post_logits, prior_logits = carry
        data = const["data"]
        # obs recomputed from the const slice (cheap normalize) instead of
        # carried from stage 1: keeps the stage chain linear — no
        # encoder→heads skip buffer alive across the whole 1F1B window
        obs = normalize_obs_block(data, cnn_keys, obs_keys)
        return _heads_losses(wm_params, data, obs, latents, post_logits, prior_logits)

    # stage grouping: the dreamer stage map has 3 units; pipeline.stages
    # picks how they fuse onto mesh sub-groups (docs/pipeline.md)
    if pipe.stages >= 3:
        if pipe.stages > 3:
            raise ValueError(
                f"pipeline.stages={pipe.stages}: the dreamer_v3 stage map has "
                "3 units (encoder, rssm, heads) — use stages in {1, 2, 3}"
            )
        stage_fns = (_enc_stage, _rssm_stage, _heads_stage)
        stage_names = ("encoder", "rssm", "heads")
    elif pipe.stages == 2:
        def _enc_rssm_stage(wm_params, _carry, const):
            embed = _enc_stage(wm_params, None, const)
            return _rssm_stage(wm_params, embed, const)

        stage_fns = (_enc_rssm_stage, _heads_stage)
        stage_names = ("encoder_rssm", "heads")
    else:
        def _wm_stage(wm_params, _carry, const):
            embed = _enc_stage(wm_params, None, const)
            carry = _rssm_stage(wm_params, embed, const)
            return _heads_stage(wm_params, carry, const)

        stage_fns = (_wm_stage,)
        stage_names = ("world_model",)

    return wm_forward, stage_fns, stage_names


def make_train_phase(
    fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
    cnn_keys, mlp_keys, is_continuous, params=None, opt_state=None,
):
    """Build the jitted multi-update train phase (shared with bench.py and
    __graft_entry__.py so the benchmarked program IS the training program).

    ``params``/``opt_state``: the already-placed state trees.  When given,
    their partition-rules shardings are pinned as the program's in/out
    shardings (``compile.state_io_shardings``) — combined with the argnum
    0/1 donation this guarantees the optimizer state stays sharded exactly
    like its params and both are updated in place across every window."""
    stoch_flat = world_model.stoch_flat
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    tau = float(cfg.algo.critic.tau)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    ent_coef = float(cfg.algo.actor.ent_coef)
    moments_cfg = cfg.algo.actor.moments
    # algo.remat: rematerialize the sequential scan bodies on the backward
    # pass (jax.checkpoint) — trades ~1 extra forward of the cell for not
    # storing L (resp. horizon) copies of its intermediates in HBM, the
    # standard lever for fitting bigger batches/sizes on-chip
    remat = bool(cfg.algo.get("remat", False))

    def maybe_remat(f):
        return jax.checkpoint(f) if remat else f

    # pipeline.* group: stage split + 1F1B microbatch schedule for the
    # world-model update, row-chunking for the imagination head evals
    # (parallel/pipeline.py, docs/pipeline.md); the disabled spec keeps the
    # monolithic pre-pipeline program op-for-op
    pipe = resolve_pipeline(cfg)
    pipe.check_algo(cfg.algo.name)
    imag_chunks = pipe.imagination_microbatches

    wm_forward, stage_fns, stage_names = make_wm_stages(
        cfg, world_model, cnn_keys, mlp_keys
    )

    if pipe.enabled:
        s_z, d_z = world_model.stochastic_size, world_model.discrete_size
        batch_aux = ("latents", "post_logits", "prior_logits")
        constrain = stage_batch_constraint(fabric.mesh, fabric.data_axis, batch_axis=1)

        def wm_value_and_grad(wm_params, data, k_wm):
            L, B = data["rewards"].shape
            keys = jax.random.split(k_wm, L)
            # full-batch noise with the baseline's exact per-timestep keys;
            # microbatch slices then sample the exact bits wm_forward would
            noise = jax.vmap(
                lambda kk: OneHotCategorical.sample_noise(kk, (B, s_z, d_z))
            )(keys)
            consts = split_microbatches(
                {"data": data, "noise": noise}, pipe.microbatches, axis=1
            )
            loss, aux_m, grads = pipeline_value_and_grad(
                stage_fns, wm_params, consts,
                microbatches=pipe.microbatches, stage_names=stage_names,
                constrain=constrain,
            )
            # reassemble: batch-shaped aux un-microbatches to (L, B, *);
            # per-microbatch scalar means average to the batch mean
            aux = {
                kk: merge_microbatches(v, axis=1) if kk in batch_aux else v.mean(0)
                for kk, v in aux_m.items()
            }
            return (loss, aux), grads
    else:
        def wm_value_and_grad(wm_params, data, k_wm):
            return jax.value_and_grad(wm_forward, has_aux=True)(wm_params, data, k_wm)

    def behavior_update(p, o_state, moments, latents, terminated, k):
        """Imagination rollout + actor and critic updates."""
        L, B = terminated.shape
        n = L * B
        start_latents = jax.lax.stop_gradient(latents.reshape(1, n, -1))[0]

        def actor_loss_fn(actor_params):
            def img_step(carry, k_t):
                h, z = carry
                latent = jnp.concatenate([z, h], -1)
                k_a, k_z = jax.random.split(k_t)
                head = actor.apply(actor_params, jax.lax.stop_gradient(latent))
                action = actor.sample(head, k_a)
                h, z = world_model.apply(
                    p["world_model"], h, z, action, k_z, method=WorldModel.imagination
                )
                return (h, z), (latent, action)

            h0 = start_latents[:, stoch_flat:]
            z0 = start_latents[:, :stoch_flat]
            keys = jax.random.split(k, horizon + 1)
            # H+1 scan steps emit the pre-action latent each time → traj holds
            # states z0, z'1, ..., z'H (reference diagram, dreamer_v3.py:222-232)
            _, (traj, actions_seq) = jax.lax.scan(maybe_remat(img_step), (h0, z0), keys)
            # predictions over the whole imagined trajectory
            # the imagination batch's wide head evals, row-chunked under
            # pipeline.imagination_microbatches (chunked_rows is fn(x)
            # verbatim at 1 — per-row values are unchanged either way)
            flat_traj = traj.reshape((horizon + 1) * n, -1)
            rewards = TwoHotEncodingDistribution(
                chunked_rows(
                    lambda x: world_model.apply(
                        p["world_model"], x, method=WorldModel.reward_logits
                    ),
                    flat_traj, imag_chunks,
                ).reshape(horizon + 1, n, -1),
                dims=1,
            ).mean[..., 0]
            values = TwoHotEncodingDistribution(
                chunked_rows(
                    lambda x: critic.apply(p["critic"], x), flat_traj, imag_chunks
                ).reshape(horizon + 1, n, -1),
                dims=1,
            ).mean[..., 0]
            continues = Bernoulli(
                chunked_rows(
                    lambda x: world_model.apply(
                        p["world_model"], x, method=WorldModel.continue_logits
                    ),
                    flat_traj, imag_chunks,
                ).reshape(horizon + 1, n)
            ).mode()
            true_continue = (1.0 - terminated).reshape(1, n)
            continues = jnp.concatenate([true_continue, continues[1:]], 0)

            lambda_values = compute_lambda_values(
                rewards[1:], values[1:], continues[1:] * gamma, lmbda
            )  # (H, n)
            discount = jnp.cumprod(continues * gamma, axis=0) / gamma  # (H+1, n)
            discount = jax.lax.stop_gradient(discount)

            new_moments, offset, invscale = moments_update(
                moments, lambda_values,
                decay=float(moments_cfg.decay), max_=float(moments_cfg.max),
                plow=float(moments_cfg.percentile.low), phigh=float(moments_cfg.percentile.high),
            )
            baseline = values[:-1]
            normed_lambda = (lambda_values - offset) / invscale
            normed_baseline = (baseline - offset) / invscale
            advantage = normed_lambda - normed_baseline  # (H, n)

            heads = actor.apply(actor_params, jax.lax.stop_gradient(traj))
            if is_continuous:
                objective = advantage
            else:
                lp = actor.log_prob(heads[:-1], jax.lax.stop_gradient(actions_seq[:-1]))
                objective = lp * jax.lax.stop_gradient(advantage)
            entropy = actor.entropy(heads[:-1])
            policy_loss = -jnp.mean(discount[:-1] * (objective + ent_coef * entropy))
            return policy_loss, (traj, lambda_values, discount)

        (pl, (traj, lambda_values, discount)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(p["actor"])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state["actor"], p["actor"])
        p = {**p, "actor": optax.apply_updates(p["actor"], a_updates)}

        # recompute moments state outside the grad fn (pure duplicate, cheap)
        new_moments, _, _ = moments_update(
            moments, lambda_values,
            decay=float(moments_cfg.decay), max_=float(moments_cfg.max),
            plow=float(moments_cfg.percentile.low), phigh=float(moments_cfg.percentile.high),
        )

        # ---- critic (Eq. 10): two-hot NLL of λ-returns + target regularizer
        traj_sg = jax.lax.stop_gradient(traj[:-1])
        flat_sg = traj_sg.reshape(horizon * traj_sg.shape[1], -1)
        target_mean = TwoHotEncodingDistribution(
            chunked_rows(
                lambda x: critic.apply(p["target_critic"], x), flat_sg, imag_chunks
            ).reshape(horizon, -1, cfg.algo.critic.bins),
            dims=1,
        ).mean

        def critic_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(
                chunked_rows(
                    lambda x: critic.apply(critic_params, x), flat_sg, imag_chunks
                ).reshape(horizon, -1, cfg.algo.critic.bins),
                dims=1,
            )
            vl = -qv.log_prob(jax.lax.stop_gradient(lambda_values)[..., None])
            vl = vl - qv.log_prob(jax.lax.stop_gradient(target_mean))
            return jnp.mean(vl * discount[:-1])

        vl, c_grads = jax.value_and_grad(critic_loss_fn)(p["critic"])
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state["critic"], p["critic"])
        p = {**p, "critic": optax.apply_updates(p["critic"], c_updates)}
        o_state = {**o_state, "actor": new_a_opt, "critic": new_c_opt}
        return p, o_state, new_moments, pl, vl

    def single_update(carry, inputs):
        p, o_state, counter = carry
        data, k = inputs  # data: dict of (L, B, *)
        k_wm, k_beh = jax.random.split(k)

        (wm_l, aux), wm_grads = wm_value_and_grad(p["world_model"], data, k_wm)
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, o_state["world_model"], p["world_model"])
        p = {**p, "world_model": optax.apply_updates(p["world_model"], wm_updates)}
        o_state = {**o_state, "world_model": new_wm_opt}

        p, o_state, new_moments, pl, vl = behavior_update(
            p, o_state, p["moments"], aux["latents"], data["terminated"], k_beh
        )
        p = {**p, "moments": new_moments}

        # target critic EMA (reference: dreamer_v3.py:674-680)
        do_ema = (counter % target_freq) == 0
        new_target = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o, p["target_critic"], p["critic"]
        )
        p = {
            **p,
            "target_critic": jax.tree.map(
                lambda n_, o_: jnp.where(do_ema, n_, o_), new_target, p["target_critic"]
            ),
        }

        post_ent = OneHotCategorical(jax.lax.stop_gradient(aux["post_logits"])).entropy().sum(-1).mean()
        prior_ent = OneHotCategorical(jax.lax.stop_gradient(aux["prior_logits"])).entropy().sum(-1).mean()
        metrics = (
            wm_l, aux["observation_loss"], aux["reward_loss"], aux["kl_loss"],
            aux["continue_loss"], aux["kl"], pl, vl, post_ent, prior_ent,
        )
        return (p, o_state, counter + 1), metrics

    def train_phase(p, o_state, blocks, k, counter0):
        U = blocks["rewards"].shape[0]
        keys = jax.random.split(k, U)
        (p, o_state, _), metrics = window_scan(
            single_update, (p, o_state, counter0), (blocks, keys), unroll=bool(cnn_keys)
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), metrics)

    in_sh = out_sh = None
    if params is not None and opt_state is not None:
        from sheeprl_tpu.parallel.compile import state_io_shardings
        from sheeprl_tpu.parallel.sharding import shardings_of

        # train_phase(p, o_state, blocks, k, counter0) -> (p, o_state, metrics)
        in_sh, out_sh = state_io_shardings(
            shardings_of(params), shardings_of(opt_state), n_extra_in=3, n_extra_out=1
        )
    return fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        in_shardings=in_sh,
        out_shardings=out_sh,
        max_recompiles=cfg.algo.get("max_recompiles"),
    )
