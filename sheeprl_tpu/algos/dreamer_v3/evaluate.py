"""DreamerV3 evaluation entrypoint (reference: sheeprl/algos/dreamer_v3/evaluate.py)."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.algos.ppo.utils import spaces_to_dims
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="dreamer_v3")
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    _evaluate_dreamer(fabric, cfg, state, build_agent)


def _evaluate_dreamer(fabric: Any, cfg: Any, state: Dict[str, Any], build_agent_fn: Any) -> None:
    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    env = make_env(cfg, cfg.seed, 0)()
    actions_dim, is_continuous = spaces_to_dims(env.action_space)
    obs_space = env.observation_space
    env.close()
    world_model, actor, critic, params = build_agent_fn(
        fabric, actions_dim, is_continuous, cfg, obs_space, state["agent"]
    )
    WM = type(world_model)
    act_width = int(sum(actions_dim))
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    stoch_flat = world_model.stoch_flat
    host_params = fabric.to_host({"world_model": params["world_model"], "actor": params["actor"]})

    @partial(jax.jit, static_argnames=("greedy",))
    def _step(p, carry, obs, k, greedy=True):
        h, z, prev_a = carry
        k_repr, k_act = jax.random.split(k)
        embed = world_model.apply(p["world_model"], obs, method=WM.encode)
        h, z, _, _ = world_model.apply(
            p["world_model"], h, z, prev_a, embed, jnp.zeros((h.shape[0], 1)), k_repr,
            method=WM.dynamic,
        )
        latent = jnp.concatenate([z, h], -1)
        action = actor.sample(actor.apply(p["actor"], latent), k_act, greedy=greedy)
        return (h, z, action), action

    def player_step_fn(p, carry, obs, k, greedy):
        if carry is None:
            carry = (
                jnp.zeros((1, rec_size)),
                jnp.zeros((1, stoch_flat)),
                jnp.zeros((1, act_width)),
            )
        carry, action = _step(p, carry, obs, k, greedy=greedy)
        a = np.asarray(action)
        if not is_continuous:
            idx, start = [], 0
            for d in actions_dim:
                idx.append(a[..., start:start + d].argmax(-1))
                start += d
            a = np.stack(idx, axis=-1).astype(np.float32)
        return carry, a

    test(player_step_fn, host_params, cfg, log_dir, logger)
