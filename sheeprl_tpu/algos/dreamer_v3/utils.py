"""DreamerV3 support utilities
(reference: sheeprl/algos/dreamer_v3/utils.py:20-235)."""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.utils import merge_framestack

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def moments_update(
    moments: Dict[str, jax.Array],
    x: jax.Array,
    decay: float = 0.99,
    max_: float = 1.0,
    plow: float = 0.05,
    phigh: float = 0.95,
) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array]:
    """Return-percentile normalizer (reference: utils.py:40-63 ``Moments``).

    The reference all-gathers across ranks before the quantile; here ``x`` is
    the GLOBAL (mesh-wide) batch inside the jitted step, so the quantile is
    already world-synchronized by GSPMD.
    Returns (new_moments, offset, invscale).
    """
    x = jax.lax.stop_gradient(x.astype(jnp.float32))
    low = jnp.quantile(x, plow)
    high = jnp.quantile(x, phigh)
    new_low = decay * moments["low"] + (1 - decay) * low
    new_high = decay * moments["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return {"low": new_low, "high": new_high}, new_low, invscale


def compute_lambda_values(
    rewards: jax.Array, values: jax.Array, continues: jax.Array, lmbda: float = 0.95
) -> jax.Array:
    """TD(λ) over imagined steps (reference: utils.py:66-77).

    Index t of every input corresponds to imagination step t+1; ``continues``
    already folds in γ.  Recursion: out[t] = r[t] + c[t]·((1-λ)·v[t] +
    λ·out[t+1]), bootstrapped with v[last].
    """

    def step(next_ret, xs):
        r, v, c = xs
        ret = r + c * ((1 - lmbda) * v + lmbda * next_ret)
        return ret, ret

    _, rets = jax.lax.scan(step, values[-1], (rewards, values, continues), reverse=True)
    return rets


def prepare_obs(
    obs: Dict[str, np.ndarray], cnn_keys: Sequence[str] = (), mlp_keys: Sequence[str] = ()
) -> Dict[str, jax.Array]:
    """uint8 images → [-0.5, 0.5] floats; vectors → float32 (the symlog is
    inside the encoder).  (reference: utils.py:80-91)."""
    out: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        x = np.asarray(obs[k])
        if x.ndim == 5:  # (B, S, H, W, C) frame stack → channels
            x = merge_framestack(x)
        out[k] = jnp.asarray(x, jnp.float32) / 255.0 - 0.5
    for k in mlp_keys:
        out[k] = jnp.asarray(np.asarray(obs[k], np.float32).reshape(np.asarray(obs[k]).shape[0], -1))
    return out


def test(
    player_step_fn: Any,
    player_state: Any,
    cfg: Any,
    log_dir: str,
    logger: Any = None,
    greedy: bool = True,
) -> float:
    """Greedy evaluation episode with the latent-state player
    (reference: utils.py:94-139)."""
    from sheeprl_tpu.algos.ppo.utils import actions_for_env
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    carry = None
    done, cum_reward = False, 0.0
    while not done:
        batched = {k: np.asarray(v)[None] for k, v in obs.items()}
        o = prepare_obs(batched, cnn_keys, mlp_keys)
        key, sk = jax.random.split(key)
        carry, env_action = player_step_fn(player_state, carry, o, sk, greedy)
        obs, reward, terminated, truncated, _ = env.step(
            actions_for_env(np.asarray(env_action), env.action_space)[0]
        )
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward


def normalize_obs_block(data, cnn_keys, obs_keys, offset: float = 0.5):
    """Device-side observation normalization of a uint8-shipped replay block:
    images → float/255 − offset, vectors → float (the jit-side twin of
    :func:`prepare_obs`)."""
    import jax.numpy as jnp

    return {
        kk: (data[kk].astype(jnp.float32) / 255.0 - offset)
        if kk in cnn_keys
        else data[kk].astype(jnp.float32)
        for kk in obs_keys
    }
