"""DreamerV3 agent (flax) — world model, actor, critic.

Capability parity with the reference agent
(reference: sheeprl/algos/dreamer_v3/agent.py:281-1236): CNN+MLP encoder with
LayerNorm/SiLU stages, RSSM (LayerNorm-GRU recurrent model, posterior /
prior MLPs over 32×32 discrete latents with 1% unimix and straight-through
gradients, learnable initial recurrent state, optional DecoupledRSSM),
CNN+MLP decoders, two-hot reward head, Bernoulli continue head, actor with
unimix discrete / clipped-Normal continuous outputs, two-hot critic.

TPU-first design:
* the RSSM is a pair of pure step functions (``rssm_dynamic``,
  ``rssm_imagination``) shaped for ``lax.scan`` — the sequence loop compiles
  into a single fused scan instead of the reference's per-step Python loop
  (reference: dreamer_v3.py:130-145);
* images are NHWC; all convs/matmuls run in the fabric's compute dtype
  (bf16 on TPU) with fp32 LayerNorm islands and fp32 heads;
* Hafner initialization = fan-avg truncated-normal for trunk layers and
  zero-init for reward/critic/continue output layers
  (reference: utils.py:143-186).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import LayerNorm, LayerNormGRUCell, get_activation
from sheeprl_tpu.utils.distribution import Bernoulli, Normal, OneHotCategorical
from sheeprl_tpu.utils.utils import symlog

trunk_init = nn.initializers.variance_scaling(1.0, "fan_avg", "truncated_normal")
zero_init = nn.initializers.zeros_init()


def _dense(units: int, dtype: Any, name: str, zero: bool = False) -> nn.Dense:
    return nn.Dense(
        units,
        use_bias=True,
        kernel_init=zero_init if zero else trunk_init,
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )


class DreamerMLP(nn.Module):
    """Dense → LayerNorm → SiLU stack (the DreamerV3 block layout)."""

    units: int
    layers: int
    output_dim: Optional[int] = None
    act: str = "silu"
    layer_norm: bool = True
    zero_head: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.act)
        x = x.astype(self.dtype)
        for i in range(self.layers):
            x = _dense(self.units, self.dtype, f"dense_{i}")(x)
            if self.layer_norm:
                x = LayerNorm(dtype=self.dtype, eps=1e-3, name=f"ln_{i}")(x)
            x = act(x)
        if self.output_dim is not None:
            x = _dense(self.output_dim, jnp.float32, "head", zero=self.zero_head)(x)
        return x


class Encoder(nn.Module):
    """CNN (stride-2 stages to 4×4) + MLP (symlog inputs) encoder
    (reference: agent.py:44-171)."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_mult: int = 32
    mlp_units: int = 512
    mlp_layers: int = 2
    act: str = "silu"
    layer_norm: bool = True
    symlog_inputs: bool = True   # V1/V2 feed raw vectors
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        act = get_activation(self.act)
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-1).astype(self.dtype)
            stages = [self.cnn_mult * m for m in (1, 2, 4, 8)]
            for i, c in enumerate(stages):
                x = nn.Conv(
                    c, (4, 4), strides=(2, 2), padding="SAME", use_bias=not self.layer_norm,
                    kernel_init=trunk_init, dtype=self.dtype, param_dtype=jnp.float32,
                    name=f"conv_{i}",
                )(x)
                if self.layer_norm:
                    x = LayerNorm(dtype=self.dtype, eps=1e-3, name=f"cnn_ln_{i}")(x)
                x = act(x)
            feats.append(x.reshape(*x.shape[:-3], -1))
        if self.mlp_keys:
            v = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            if self.symlog_inputs:
                v = symlog(v)
            feats.append(
                DreamerMLP(
                    units=self.mlp_units, layers=self.mlp_layers, act=self.act,
                    layer_norm=self.layer_norm, dtype=self.dtype, name="mlp_encoder",
                )(v)
            )
        return jnp.concatenate(feats, axis=-1)


class Decoder(nn.Module):
    """Latent → CNN transpose stages + MLP heads
    (reference: agent.py:174-278).  Returns per-key reconstruction means."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_shapes: Dict[str, Tuple[int, int, int]]
    mlp_shapes: Dict[str, int]
    cnn_mult: int = 32
    mlp_units: int = 512
    mlp_layers: int = 2
    act: str = "silu"
    layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        act = get_activation(self.act)
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            total_c = sum(self.cnn_shapes[k][-1] for k in self.cnn_keys)
            x = _dense(4 * 4 * self.cnn_mult * 8, self.dtype, "cnn_in")(latent.astype(self.dtype))
            x = x.reshape(*x.shape[:-1], 4, 4, self.cnn_mult * 8)
            for i, c in enumerate((self.cnn_mult * 4, self.cnn_mult * 2, self.cnn_mult)):
                x = nn.ConvTranspose(
                    c, (4, 4), strides=(2, 2), padding="SAME", use_bias=not self.layer_norm,
                    kernel_init=trunk_init, dtype=self.dtype, param_dtype=jnp.float32,
                    name=f"deconv_{i}",
                )(x)
                if self.layer_norm:
                    x = LayerNorm(dtype=self.dtype, eps=1e-3, name=f"cnn_ln_{i}")(x)
                x = act(x)
            x = nn.ConvTranspose(
                total_c, (4, 4), strides=(2, 2), padding="SAME",
                kernel_init=trunk_init, dtype=jnp.float32, param_dtype=jnp.float32,
                name="deconv_out",
            )(x)
            start = 0
            for k in self.cnn_keys:
                c = self.cnn_shapes[k][-1]
                out[k] = x[..., start:start + c]
                start += c
        if self.mlp_keys:
            trunk = DreamerMLP(
                units=self.mlp_units, layers=self.mlp_layers, act=self.act,
                dtype=self.dtype, name="mlp_decoder",
            )(latent)
            for k in self.mlp_keys:
                out[k] = _dense(self.mlp_shapes[k], jnp.float32, f"head_{k}")(trunk)
        return out


class RecurrentModel(nn.Module):
    """(z ⊕ a) → dense+LN+SiLU → LayerNormGRUCell (reference: agent.py:281-341).

    ``fused_pallas`` runs the WHOLE path as one VMEM-resident Pallas kernel
    (ops/rssm_pallas.py): both weight blocks live in VMEM and the ``(B, D)``
    and ``(B, 3H)`` intermediates never round-trip HBM between the scan
    steps.  NOTE: the fused path declares flat params (different checkpoint
    layout than the flax submodules — pick the flag at model-creation time,
    same caveat as LayerNormGRUCell.use_pallas).
    """

    recurrent_size: int
    dense_units: int
    use_pallas: bool = False  # fused VMEM-resident GRU kernel only (TPU)
    fused_pallas: bool = False  # full dense+LN+SiLU+GRU one-kernel path (TPU)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> jax.Array:
        if self.fused_pallas:
            from sheeprl_tpu.ops.rssm_pallas import fused_rssm_recurrent

            D, H = self.dense_units, self.recurrent_size
            w_in = self.param("in_kernel", trunk_init, (x.shape[-1], D), jnp.float32)
            b_in = self.param("in_bias", nn.initializers.zeros_init(), (D,), jnp.float32)
            ln_s = self.param("ln_scale", nn.initializers.ones_init(), (D,), jnp.float32)
            ln_b = self.param("ln_bias", nn.initializers.zeros_init(), (D,), jnp.float32)
            w_gru = self.param(
                "gru_kernel", nn.initializers.lecun_normal(), (D + H, 3 * H), jnp.float32
            )
            g_s = self.param("gru_ln_scale", nn.initializers.ones_init(), (3 * H,), jnp.float32)
            g_b = self.param("gru_ln_bias", nn.initializers.zeros_init(), (3 * H,), jnp.float32)
            return fused_rssm_recurrent(
                x, h, w_in, b_in, ln_s, ln_b, w_gru, g_s, g_b
            ).astype(self.dtype)
        y = _dense(self.dense_units, self.dtype, "in")(x.astype(self.dtype))
        y = LayerNorm(dtype=self.dtype, eps=1e-3, name="ln")(y)
        y = nn.silu(y)
        new_h, _ = LayerNormGRUCell(
            units=self.recurrent_size, layer_norm=True, use_pallas=self.use_pallas,
            dtype=self.dtype, name="gru",
        )(h, y)
        return new_h


class WorldModel(nn.Module):
    """Container module: encoder, RSSM parts, decoders, reward/continue heads
    (reference: agent.py:707-732 structure for DV2/DV3)."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_shapes: Dict[str, Tuple[int, int, int]]
    mlp_shapes: Dict[str, int]
    actions_dim: Tuple[int, ...]
    cnn_mult: int = 32
    dense_units: int = 512
    mlp_layers: int = 2
    recurrent_size: int = 512
    hidden_size: int = 512           # transition (prior) MLP width
    repr_hidden_size: int = 512      # representation (posterior) MLP width
    stochastic_size: int = 32
    discrete_size: int = 32
    unimix: float = 0.01
    bins: int = 255
    act: str = "silu"
    layer_norm: bool = True
    symlog_inputs: bool = True
    learnable_initial_state: bool = True
    decoupled_rssm: bool = False
    use_pallas_gru: bool = False
    fused_pallas_rssm: bool = False
    dtype: Any = jnp.float32

    @property
    def stoch_flat(self) -> int:
        return self.stochastic_size * self.discrete_size

    def setup(self) -> None:
        self.encoder = Encoder(
            cnn_keys=self.cnn_keys, mlp_keys=self.mlp_keys, cnn_mult=self.cnn_mult,
            mlp_units=self.dense_units, mlp_layers=self.mlp_layers, act=self.act,
            layer_norm=self.layer_norm, symlog_inputs=self.symlog_inputs,
            dtype=self.dtype, name="encoder",
        )
        self.recurrent_model = RecurrentModel(
            recurrent_size=self.recurrent_size, dense_units=self.dense_units,
            use_pallas=self.use_pallas_gru, fused_pallas=self.fused_pallas_rssm,
            dtype=self.dtype, name="recurrent_model",
        )
        # posterior: (h ⊕ embed) → logits; prior: h → logits
        self.representation_model = DreamerMLP(
            units=self.repr_hidden_size, layers=1, output_dim=self.stoch_flat,
            act=self.act, layer_norm=self.layer_norm, dtype=self.dtype,
            name="representation_model",
        )
        self.transition_model = DreamerMLP(
            units=self.hidden_size, layers=1, output_dim=self.stoch_flat,
            act=self.act, layer_norm=self.layer_norm, dtype=self.dtype,
            name="transition_model",
        )
        self.observation_model = Decoder(
            cnn_keys=self.cnn_keys, mlp_keys=self.mlp_keys, cnn_shapes=self.cnn_shapes,
            mlp_shapes=self.mlp_shapes, cnn_mult=self.cnn_mult, mlp_units=self.dense_units,
            mlp_layers=self.mlp_layers, act=self.act, layer_norm=self.layer_norm,
            dtype=self.dtype, name="observation_model",
        )
        self.reward_model = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, output_dim=self.bins,
            act=self.act, layer_norm=self.layer_norm, zero_head=True,
            dtype=self.dtype, name="reward_model",
        )
        self.continue_model = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, output_dim=1,
            act=self.act, layer_norm=self.layer_norm, zero_head=True,
            dtype=self.dtype, name="continue_model",
        )
        if self.learnable_initial_state:
            self.initial_recurrent = self.param(
                "initial_recurrent", zero_init, (self.recurrent_size,), jnp.float32
            )

    # ---- pieces (exposed as module methods for apply(..., method=...)) ----
    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder(obs)

    def initial_state(self, batch: int) -> Tuple[jax.Array, jax.Array]:
        """(h0, z0): learnable tanh'd recurrent init; z0 = prior mode of h0."""
        if self.learnable_initial_state:
            h0 = jnp.tanh(self.initial_recurrent.astype(jnp.float32))
        else:
            h0 = jnp.zeros((self.recurrent_size,), jnp.float32)
        h0 = jnp.broadcast_to(h0, (batch, self.recurrent_size))
        prior_logits = self._logits_reshape(self.transition_model(h0))
        z0 = OneHotCategorical(prior_logits, unimix=self.unimix).mode()
        return h0, z0.reshape(batch, self.stoch_flat)

    def _logits_reshape(self, logits: jax.Array) -> jax.Array:
        return logits.reshape(*logits.shape[:-1], self.stochastic_size, self.discrete_size)

    def dynamic(
        self,
        prev_h: jax.Array,
        prev_z: jax.Array,
        prev_action: jax.Array,
        embed: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """One posterior step (reference RSSM.dynamic, agent.py:430-470).

        Resets (h, z, a) at episode starts, advances the GRU, computes prior
        and posterior logits, samples the posterior (straight-through).
        Returns (h, z, posterior_logits, prior_logits).
        """
        B = prev_h.shape[0]
        h0, z0 = self.initial_state(B)
        mask = 1.0 - is_first  # (B, 1)
        prev_h = prev_h * mask + h0 * is_first
        prev_z = prev_z * mask + z0 * is_first
        prev_action = prev_action * mask
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, prev_action], -1))
        h = h.astype(jnp.float32)  # fp32 carried state under bf16 compute
        prior_logits = self._logits_reshape(self.transition_model(h))
        if self.decoupled_rssm:
            # DecoupledRSSM (reference: agent.py:501-593): the posterior does
            # NOT see the recurrent state — it becomes embarrassingly
            # parallel over time (computed outside the scan on TPU).
            post_logits = self._logits_reshape(self.representation_model(embed))
        else:
            post_logits = self._logits_reshape(
                self.representation_model(jnp.concatenate([h, embed], -1))
            )
        z = OneHotCategorical(post_logits, unimix=self.unimix).rsample(key)
        return h, z.reshape(B, self.stoch_flat), post_logits, prior_logits

    def dynamic_noise(
        self,
        prev_h: jax.Array,
        prev_z: jax.Array,
        prev_action: jax.Array,
        embed: jax.Array,
        is_first: jax.Array,
        noise: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """:meth:`dynamic` with pre-drawn sampling noise instead of a key —
        the pipeline sample-invariance form (parallel/pipeline.py).

        ``noise`` is a row-slice of ``OneHotCategorical.sample_noise`` drawn
        at the FULL batch's posterior-logits shape with the same key
        :meth:`dynamic` would consume, which makes this bit-identical to
        :meth:`dynamic` on the corresponding batch rows regardless of how
        the batch was microbatched (argmax is rowwise)."""
        B = prev_h.shape[0]
        h0, z0 = self.initial_state(B)
        mask = 1.0 - is_first  # (B, 1)
        prev_h = prev_h * mask + h0 * is_first
        prev_z = prev_z * mask + z0 * is_first
        prev_action = prev_action * mask
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, prev_action], -1))
        h = h.astype(jnp.float32)  # fp32 carried state under bf16 compute
        prior_logits = self._logits_reshape(self.transition_model(h))
        if self.decoupled_rssm:
            post_logits = self._logits_reshape(self.representation_model(embed))
        else:
            post_logits = self._logits_reshape(
                self.representation_model(jnp.concatenate([h, embed], -1))
            )
        z = OneHotCategorical(post_logits, unimix=self.unimix).rsample_from_noise(noise)
        return h, z.reshape(B, self.stoch_flat), post_logits, prior_logits

    def posterior_decoupled(self, embed: jax.Array) -> jax.Array:
        """DecoupledRSSM posterior logits from the embedding ALONE — batched
        over all timesteps at once (the whole point of the variant on TPU:
        the posterior leaves the sequential scan, reference: agent.py:501-593)."""
        return self._logits_reshape(self.representation_model(embed))

    def recurrent_prior(
        self, prev_h: jax.Array, prev_z: jax.Array, prev_action: jax.Array, is_first: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """The only sequential piece of the DecoupledRSSM: advance the GRU and
        predict the prior; posteriors are precomputed in parallel."""
        B = prev_h.shape[0]
        h0, z0 = self.initial_state(B)
        mask = 1.0 - is_first
        prev_h = prev_h * mask + h0 * is_first
        prev_z = prev_z * mask + z0 * is_first
        prev_action = prev_action * mask
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, prev_action], -1))
        h = h.astype(jnp.float32)
        prior_logits = self._logits_reshape(self.transition_model(h))
        return h, prior_logits

    def imagination(
        self, prev_h: jax.Array, prev_z: jax.Array, action: jax.Array, key: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """One prior step (reference RSSM.imagination, agent.py:472-499)."""
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, action], -1))
        h = h.astype(jnp.float32)
        prior_logits = self._logits_reshape(self.transition_model(h))
        z = OneHotCategorical(prior_logits, unimix=self.unimix).rsample(key)
        return h, z.reshape(z.shape[0], self.stoch_flat)

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        return self.observation_model(latent)

    def reward_logits(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent)

    def continue_logits(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent)

    def __call__(self, obs, prev_h, prev_z, prev_action, is_first, key):
        """Single full step — used only for parameter initialization."""
        embed = self.encode(obs)
        h, z, post, prior = self.dynamic(prev_h, prev_z, prev_action, embed, is_first, key)
        latent = jnp.concatenate([z, h], -1)
        recon = self.decode(latent)
        return h, z, post, prior, recon, self.reward_logits(latent), self.continue_logits(latent)


class Actor(nn.Module):
    """Latent → action distribution (reference: agent.py:596-704).

    Discrete: per-branch unimix categoricals (straight-through sampling).
    Continuous: Normal with sigmoid-squashed std in [min_std, max_std] and
    clipped samples (action_clip).
    """

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    dense_units: int = 512
    mlp_layers: int = 2
    act: str = "silu"
    layer_norm: bool = True
    unimix: float = 0.01
    min_std: float = 0.1
    max_std: float = 1.0
    init_std: float = 2.0
    action_clip: float = 1.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> jax.Array:
        trunk = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, act=self.act,
            layer_norm=self.layer_norm, dtype=self.dtype, name="trunk",
        )(latent)
        out_dim = sum(self.actions_dim) * (2 if self.is_continuous else 1)
        return _dense(out_dim, jnp.float32, "head")(trunk)

    # -- distribution helpers (static, operate on head output) --------------
    def dists(self, head_out: jax.Array):
        if self.is_continuous:
            mean, std_raw = jnp.split(head_out, 2, axis=-1)
            std = (self.max_std - self.min_std) * nn.sigmoid(std_raw + self.init_std) + self.min_std
            return [Normal(jnp.tanh(mean), std, event_dims=1)]
        dists = []
        start = 0
        for d in self.actions_dim:
            dists.append(OneHotCategorical(head_out[..., start:start + d], unimix=self.unimix))
            start += d
        return dists

    def sample(self, head_out: jax.Array, key: jax.Array, greedy: bool = False) -> jax.Array:
        dists = self.dists(head_out)
        if self.is_continuous:
            d = dists[0]
            a = d.mode() if greedy else d.sample(key)
            if self.action_clip > 0:
                # Gradient-preserving scaled clip (reference: dreamer_v3/agent.py
                # Actor.forward): a hard clip would zero d(action)/d(params) for
                # saturated samples and cut the dynamics-backprop signal.
                scale = jax.lax.stop_gradient(
                    self.action_clip / jnp.maximum(self.action_clip, jnp.abs(a))
                )
                a = a * scale
            return a
        keys = jax.random.split(key, len(dists))
        parts = [
            (d.mode() if greedy else d.rsample(k)) for d, k in zip(dists, keys)
        ]
        return jnp.concatenate(parts, axis=-1)

    def sample_masked(
        self,
        head_out: jax.Array,
        key: jax.Array,
        masks: Dict[str, jax.Array],
        greedy: bool = False,
    ) -> jax.Array:
        """MineDojo-style masked sampling (reference: dreamer_v3/agent.py
        MinedojoActor.forward) — fully vectorized, no Python loops over the
        batch, so it jits onto the host player unchanged.

        Branch 0 (the compound action) is masked by ``mask_action_type``;
        branch 1 (the craft argument) by ``mask_craft_smelt`` but only where
        branch 0 sampled the craft action; branch 2 (the inventory argument)
        by ``mask_equip_place`` / ``mask_destroy`` where branch 0 sampled
        equip/place / destroy.  Masks arrive as float observations (the env
        exposes them as obs keys); nonzero means allowed.  Masking happens
        AFTER the unimix so excluded actions get exactly zero probability.
        """
        from sheeprl_tpu.envs.minedojo import (
            FN_CRAFT,
            FN_DESTROY,
            FN_EQUIP,
            FN_PLACE,
            N_MOVEMENT_ACTIONS,
        )

        def masked(logits: jax.Array, allowed: jax.Array) -> OneHotCategorical:
            return OneHotCategorical(jnp.where(allowed > 0, logits, -1e9))

        dists = self.dists(head_out)  # unimix already folded into .logits
        keys = jax.random.split(key, len(dists))
        d0 = masked(dists[0].logits, masks["mask_action_type"])
        a0 = d0.mode() if greedy else d0.sample(keys[0])
        compound_idx = jnp.argmax(a0, -1)
        parts = [a0]

        if len(dists) > 1:  # craft/smelt argument
            is_craft = (compound_idx == N_MOVEMENT_ACTIONS + FN_CRAFT - 1)[..., None]
            allowed = jnp.where(is_craft, masks["mask_craft_smelt"] > 0, True)
            d1 = masked(dists[1].logits, allowed)
            parts.append(d1.mode() if greedy else d1.sample(keys[1]))
        if len(dists) > 2:  # inventory-item argument
            is_equip_place = (
                (compound_idx == N_MOVEMENT_ACTIONS + FN_EQUIP - 1)
                | (compound_idx == N_MOVEMENT_ACTIONS + FN_PLACE - 1)
            )[..., None]
            is_destroy = (compound_idx == N_MOVEMENT_ACTIONS + FN_DESTROY - 1)[..., None]
            allowed = jnp.where(
                is_equip_place,
                masks["mask_equip_place"] > 0,
                jnp.where(is_destroy, masks["mask_destroy"] > 0, True),
            )
            d2 = masked(dists[2].logits, allowed)
            parts.append(d2.mode() if greedy else d2.sample(keys[2]))
        return jnp.concatenate(parts, axis=-1)

    def log_prob(self, head_out: jax.Array, actions: jax.Array) -> jax.Array:
        dists = self.dists(head_out)
        if self.is_continuous:
            return dists[0].log_prob(actions)
        lp, start = 0.0, 0
        for d, dim in zip(dists, self.actions_dim):
            lp = lp + d.log_prob(actions[..., start:start + dim])
            start += dim
        return lp

    def entropy(self, head_out: jax.Array) -> jax.Array:
        dists = self.dists(head_out)
        return sum(d.entropy() for d in dists)


class Critic(nn.Module):
    """Latent → two-hot bins (reference: agent.py critic MLP, bins=255)."""

    dense_units: int = 512
    mlp_layers: int = 2
    act: str = "silu"
    layer_norm: bool = True
    bins: int = 255
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> jax.Array:
        x = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, act=self.act,
            layer_norm=self.layer_norm, dtype=self.dtype, name="trunk",
        )(latent)
        return _dense(self.bins, jnp.float32, "head", zero=True)(x)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Any,
    obs_space: Any,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[WorldModel, Actor, Critic, Dict[str, Any]]:
    """Construct modules + params {world_model, actor, critic, target_critic}
    (reference: agent.py:935-1236)."""
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    wm_cfg = cfg.algo.world_model
    cnn_shapes = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:  # frame-stacked: merged into channels
            shape = (shape[1], shape[2], shape[0] * shape[3])
        cnn_shapes[k] = tuple(shape)
    mlp_shapes = {k: int(np.prod(obs_space[k].shape)) for k in mlp_keys}

    dtype = fabric.precision.compute_dtype
    world_model = WorldModel(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_shapes=cnn_shapes,
        mlp_shapes=mlp_shapes,
        actions_dim=tuple(actions_dim),
        cnn_mult=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        recurrent_size=wm_cfg.recurrent_model.recurrent_state_size,
        hidden_size=wm_cfg.transition_model.hidden_size,
        repr_hidden_size=wm_cfg.representation_model.hidden_size,
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        unimix=cfg.algo.unimix,
        bins=wm_cfg.reward_model.bins,
        learnable_initial_state=wm_cfg.learnable_initial_recurrent_state,
        decoupled_rssm=wm_cfg.decoupled_rssm,
        use_pallas_gru=bool(wm_cfg.recurrent_model.get("use_pallas", False)),
        fused_pallas_rssm=bool(wm_cfg.recurrent_model.get("fused_pallas", False)),
        dtype=dtype,
    )
    if fabric.model_axis and (
        bool(wm_cfg.recurrent_model.get("use_pallas", False))
        or bool(wm_cfg.recurrent_model.get("fused_pallas", False))
    ):
        # the partition rules column-shard 2-D kernels over the model axis;
        # a pallas_call would receive a sharded w_gru operand — at best a
        # silent all-gather per step, at worst a Mosaic compile failure.
        # Enforce the howto/run_on_tpu.md exclusion instead of hoping (ADVICE r3)
        raise ValueError(
            "tensor parallelism (fabric.mesh_shape with a model axis) cannot "
            "be combined with the Pallas RSSM kernels: the partition rules "
            "(docs/sharding.md) would shard the GRU kernel under the "
            "single-device pallas_call. Disable "
            "algo.world_model.recurrent_model.{use_pallas,fused_pallas} or "
            "run without a model axis."
        )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        unimix=cfg.algo.actor.unimix,
        min_std=cfg.algo.actor.min_std,
        max_std=cfg.algo.actor.max_std,
        init_std=cfg.algo.actor.init_std,
        action_clip=cfg.algo.actor.action_clip,
        dtype=dtype,
    )
    critic = Critic(
        dense_units=cfg.algo.critic.dense_units,
        mlp_layers=cfg.algo.critic.mlp_layers,
        bins=cfg.algo.critic.bins,
        dtype=dtype,
    )
    if state is not None:
        params = state
    else:
        key = jax.random.PRNGKey(cfg.seed)
        k_wm, k_actor, k_critic, k_s = jax.random.split(key, 4)
        dummy_obs = {}
        for k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), jnp.float32)
        for k in mlp_keys:
            dummy_obs[k] = jnp.zeros((1, mlp_shapes[k]), jnp.float32)
        stoch = wm_cfg.stochastic_size * wm_cfg.discrete_size
        rec = wm_cfg.recurrent_model.recurrent_state_size
        act_width = int(sum(actions_dim))
        wm_params = world_model.init(
            k_wm,
            dummy_obs,
            jnp.zeros((1, rec)),
            jnp.zeros((1, stoch)),
            jnp.zeros((1, act_width)),
            jnp.ones((1, 1)),
            k_s,
        )
        latent = jnp.zeros((1, stoch + rec))
        actor_params = actor.init(k_actor, latent)
        critic_params = critic.init(k_critic, latent)
        params = {
            "world_model": wm_params,
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "moments": {"low": jnp.zeros(()), "high": jnp.zeros(())},
        }
    # shard_params: replicated on a pure-data mesh; with fabric.mesh_shape
    # declaring a model axis, placement follows the partition-rule tables of
    # parallel/sharding.py (curated dreamer_v3 table under sharding.table=auto:
    # RSSM dense stacks + GRU gates column-shard, decoder deconvs on output
    # channels, MLP heads row-shard) — docs/sharding.md
    return world_model, actor, critic, fabric.shard_params(params)
