"""DreamerV3 world-model loss (reference: sheeprl/algos/dreamer_v3/loss.py:9-88)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.distribution import OneHotCategorical, kl_categorical


def world_model_loss(
    obs_log_probs: Dict[str, jax.Array],
    reward_log_prob: jax.Array,
    continue_log_prob: jax.Array,
    posterior_logits: jax.Array,
    prior_logits: jax.Array,
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    continue_scale_factor: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Eq. 5 of the DreamerV3 paper: reconstruction + reward + continue NLL
    plus free-nats-clipped balanced KL.

    All *_log_prob arrays are (T, B); logits are (T, B, stoch, discrete).
    KL is summed over the stochastic axis (Independent(·, 1) semantics).
    """
    observation_loss = -sum(obs_log_probs.values())
    reward_loss = -reward_log_prob
    continue_loss = -continue_scale_factor * continue_log_prob

    post = OneHotCategorical(posterior_logits)
    post_sg = OneHotCategorical(jax.lax.stop_gradient(posterior_logits))
    prior = OneHotCategorical(prior_logits)
    prior_sg = OneHotCategorical(jax.lax.stop_gradient(prior_logits))

    kl = kl_categorical(post_sg, prior).sum(-1)  # sum over stochastic axis
    dyn_loss = kl_dynamic * jnp.maximum(kl, kl_free_nats)
    repr_loss = kl_representation * jnp.maximum(
        kl_categorical(post, prior_sg).sum(-1), kl_free_nats
    )
    kl_loss = dyn_loss + repr_loss

    total = jnp.mean(kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss)
    aux = {
        "kl": kl.mean(),
        "kl_loss": kl_loss.mean(),
        "observation_loss": observation_loss.mean(),
        "reward_loss": reward_loss.mean(),
        "continue_loss": continue_loss.mean(),
    }
    return total, aux
