"""Plan2Explore over DreamerV1 — exploration phase
(reference: sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py:207-330).

An ensemble of N forward models is trained to predict the next stochastic
state from (latent ⊕ action); its prediction variance is the intrinsic
reward.  Two separate policies train every step, matching the reference:

* the EXPLORATION actor (the one the player acts with) and its own
  ``critic_exploration`` learn the pure intrinsic return;
* the TASK actor (``actor_task``) and the task critic learn the extrinsic
  return, so finetuning starts from a task policy.

Both run inside DreamerV1's single-dispatch scanned train phase via the
``p2e`` hook (see dreamer_v1.make_train_phase).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as base_build_agent
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_phase as base_make_train_phase
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm


def build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state=None):
    world_model, actor, critic, params = base_build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space, state
    )
    rec = cfg.algo.world_model.recurrent_model.recurrent_state_size
    latent_dim = world_model.stoch_flat + rec
    key = jax.random.PRNGKey(cfg.seed + 1)
    k_ens, k_actor, k_critic = jax.random.split(key, 3)
    dummy_latent = jnp.zeros((1, latent_dim))
    if state is not None:
        # resume path: backfill P2E-only params a pre-dual-policy
        # checkpoint may lack
        saved = jax.device_get(params)
        missing = {}
        if "actor_task" not in saved:
            missing["actor_task"] = actor.init(k_actor, dummy_latent)
        if "critic_exploration" not in saved:
            missing["critic_exploration"] = critic.init(k_critic, dummy_latent)
        if missing:
            params = fabric.replicate({**saved, **missing})
        return world_model, actor, critic, params
    ens = _ensemble(cfg, world_model)
    ens_params = ens.init(k_ens, jnp.zeros((1, latent_dim + int(sum(actions_dim)))))
    params = jax.device_get(params)
    params = {
        **params,
        "ensembles": ens_params,
        # "actor" is the exploration policy (the player acts with it);
        # the task policy trains alongside on extrinsic rewards
        "actor_task": actor.init(k_actor, dummy_latent),
        "critic_exploration": critic.init(k_critic, dummy_latent),
    }
    return world_model, actor, critic, fabric.replicate(params)


def _ensemble(cfg, world_model):
    import flax.linen as nn

    from sheeprl_tpu.algos.dreamer_v3.agent import DreamerMLP

    class Ensembles(nn.Module):
        @nn.compact
        def __call__(self, x):
            net = nn.vmap(
                DreamerMLP, in_axes=None, out_axes=0,
                axis_size=int(cfg.algo.ensembles.n),
                variable_axes={"params": 0}, split_rngs={"params": True},
            )
            return net(
                units=cfg.algo.ensembles.dense_units,
                layers=cfg.algo.ensembles.mlp_layers,
                output_dim=world_model.stoch_flat,
                act=cfg.algo.dense_act,
                layer_norm=False,
                name="ens",
            )(x)

    return Ensembles()


def make_train_phase(fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
                     cnn_keys, mlp_keys, is_continuous, params=None, opt_state=None):
    p2e = {
        "ens_module": _ensemble(cfg, world_model),
        "ens_opt": build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients),
        "n": int(cfg.algo.ensembles.n),
        "multiplier": float(cfg.algo.intrinsic_reward_multiplier),
    }
    return base_make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys, mlp_keys, is_continuous, p2e=p2e, params=params, opt_state=opt_state,
    )


def build_optimizers(fabric, cfg, params, saved=None):
    wm_opt = build_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ens_opt = build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)
    factories = {
        "world_model": lambda: wm_opt.init(params["world_model"]),
        "actor": lambda: actor_opt.init(params["actor"]),
        "actor_task": lambda: actor_opt.init(params["actor_task"]),
        "critic": lambda: critic_opt.init(params["critic"]),
        "critic_exploration": lambda: critic_opt.init(params["critic_exploration"]),
        "ensembles": lambda: ens_opt.init(params["ensembles"]),
    }
    # saved states from pre-dual-policy checkpoints lack the new entries
    opt_state = fabric.replicate(
        {k: (saved[k] if saved and k in saved else init()) for k, init in factories.items()}
    )
    return wm_opt, actor_opt, critic_opt, opt_state


@register_algorithm(name="p2e_dv1_exploration")
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    dreamer_family_loop(
        fabric, cfg, build_agent, make_train_phase, optimizer_builder=build_optimizers
    )
