"""P2E DV1 evaluation (reference: sheeprl/algos/p2e_dv1/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as base_build_agent
from sheeprl_tpu.algos.dreamer_v3.evaluate import _evaluate_dreamer
from sheeprl_tpu.algos.p2e_utils import choose_actor
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv1_exploration", "p2e_dv1_finetuning"], name="p2e_dv1")
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    agent = dict(state["agent"])
    agent.pop("ensembles", None)
    _evaluate_dreamer(fabric, cfg, {"agent": choose_actor(agent, cfg)}, base_build_agent)
