"""Plan2Explore over DreamerV1 — finetuning phase
(reference: sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py).

Reloads the exploration checkpoint's world model and — by default — its
TASK actor/critic (``algo.player.actor_type=task``; ``exploration`` starts
from the exploration policy instead, as the reference does before its
learning-starts switch) and continues with standard DreamerV1 training."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as base_build_agent
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_phase as base_make_train_phase
from sheeprl_tpu.algos.p2e_utils import actor_type_from_cfg, project_exploration_state
from sheeprl_tpu.config.compose import ConfigError
from sheeprl_tpu.utils.registry import register_algorithm


def exploration_state_to_dv1(state: Dict[str, Any], actor_type: str = "task") -> Dict[str, Any]:
    """Project an exploration-phase checkpoint onto the DV1 state layout
    (world model + TASK critic, actor chosen by ``actor_type``)."""
    return project_exploration_state(state, actor_type, keep_keys=("world_model", "critic"))


@register_algorithm(name="p2e_dv1_finetuning")
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    ckpt_path = cfg.checkpoint.get("exploration_ckpt_path")
    initial_state = None
    if ckpt_path:
        raw = fabric.load(ckpt_path)
        initial_state = exploration_state_to_dv1(raw, actor_type=actor_type_from_cfg(cfg))
        if not cfg.buffer.get("load_from_exploration", False):
            initial_state.pop("rb", None)
    elif not cfg.checkpoint.resume_from:
        raise ConfigError("p2e finetuning needs checkpoint.exploration_ckpt_path")
    dreamer_family_loop(
        fabric, cfg, base_build_agent, base_make_train_phase, initial_state=initial_state
    )
