"""Plan2Explore over DreamerV1 — finetuning phase
(reference: sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py)."""

from __future__ import annotations

from typing import Any

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as base_build_agent
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_phase as base_make_train_phase
from sheeprl_tpu.config.compose import ConfigError
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm(name="p2e_dv1_finetuning")
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    ckpt_path = cfg.checkpoint.get("exploration_ckpt_path")
    initial_state = None
    if ckpt_path:
        raw = fabric.load(ckpt_path)
        agent = dict(raw["agent"])
        agent.pop("ensembles", None)
        initial_state = {"agent": agent}
        if cfg.buffer.get("load_from_exploration", False) and "rb" in raw:
            initial_state["rb"] = raw["rb"]
    elif not cfg.checkpoint.resume_from:
        raise ConfigError("p2e finetuning needs checkpoint.exploration_ckpt_path")
    dreamer_family_loop(
        fabric, cfg, base_build_agent, base_make_train_phase, initial_state=initial_state
    )
