"""Plan2Explore (p2e_dv1)."""
