"""DreamerV1 evaluation entrypoint (reference: sheeprl/algos/dreamer_v1/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.evaluate import _evaluate_dreamer
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="dreamer_v1")
def evaluate(fabric: Any, cfg: Any, state: Dict[str, Any]) -> None:
    _evaluate_dreamer(fabric, cfg, state, build_agent)
