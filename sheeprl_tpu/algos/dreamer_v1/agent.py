"""DreamerV1 agent (flax).

Capability parity with the reference (reference: sheeprl/algos/dreamer_v1/
agent.py:1-547): RSSM with CONTINUOUS Gaussian latents (mean + softplus
std + min_std), plain-KL world model, Gaussian observation/reward heads,
value network, dynamics-backprop actor.  Shares the encoder/decoder/
recurrent-cell family with the V2/V3 implementation, configured without
LayerNorm stages (the reference uses plain conv/dense + ELU).

The module exposes the same method surface as the discrete ``WorldModel``
(``encode``/``dynamic``/``imagination``/``decode``/heads) so the shared
Dreamer family loop and player drive it unchanged; ``dynamic`` returns the
posterior/prior (mean‖std) stacked where V3 returns categorical logits.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    Critic,
    Decoder,
    DreamerMLP,
    Encoder,
    RecurrentModel,
)


class GaussianWorldModel(nn.Module):
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_shapes: Dict[str, Tuple[int, int, int]]
    mlp_shapes: Dict[str, int]
    actions_dim: Tuple[int, ...]
    cnn_mult: int = 32
    dense_units: int = 400
    mlp_layers: int = 4
    recurrent_size: int = 200
    hidden_size: int = 200
    stochastic_size: int = 30
    min_std: float = 0.1
    act: str = "elu"
    dtype: Any = jnp.float32

    @property
    def stoch_flat(self) -> int:
        return self.stochastic_size

    def setup(self) -> None:
        self.encoder = Encoder(
            cnn_keys=self.cnn_keys, mlp_keys=self.mlp_keys, cnn_mult=self.cnn_mult,
            mlp_units=self.dense_units, mlp_layers=self.mlp_layers, act=self.act,
            layer_norm=False, symlog_inputs=False, dtype=self.dtype, name="encoder",
        )
        self.recurrent_model = RecurrentModel(
            recurrent_size=self.recurrent_size, dense_units=self.dense_units,
            dtype=self.dtype, name="recurrent_model",
        )
        self.representation_model = DreamerMLP(
            units=self.hidden_size, layers=1, output_dim=2 * self.stochastic_size,
            act=self.act, layer_norm=False, dtype=self.dtype, name="representation_model",
        )
        self.transition_model = DreamerMLP(
            units=self.hidden_size, layers=1, output_dim=2 * self.stochastic_size,
            act=self.act, layer_norm=False, dtype=self.dtype, name="transition_model",
        )
        self.observation_model = Decoder(
            cnn_keys=self.cnn_keys, mlp_keys=self.mlp_keys, cnn_shapes=self.cnn_shapes,
            mlp_shapes=self.mlp_shapes, cnn_mult=self.cnn_mult, mlp_units=self.dense_units,
            mlp_layers=self.mlp_layers, act=self.act, layer_norm=False,
            dtype=self.dtype, name="observation_model",
        )
        self.reward_model = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, output_dim=1,
            act=self.act, layer_norm=False, dtype=self.dtype, name="reward_model",
        )
        self.continue_model = DreamerMLP(
            units=self.dense_units, layers=self.mlp_layers, output_dim=1,
            act=self.act, layer_norm=False, dtype=self.dtype, name="continue_model",
        )

    # -- helpers -------------------------------------------------------------
    def _moments(self, raw: jax.Array) -> Tuple[jax.Array, jax.Array]:
        mean, std_raw = jnp.split(raw, 2, axis=-1)
        std = jax.nn.softplus(std_raw) + self.min_std
        return mean, std

    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder(obs)

    def dynamic(self, prev_h, prev_z, prev_action, embed, is_first, key):
        """Posterior step: returns (h, z, post_moments, prior_moments) where
        moments = mean‖std stacked on the last axis."""
        mask = 1.0 - is_first
        prev_h = prev_h * mask
        prev_z = prev_z * mask
        prev_action = prev_action * mask
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, prev_action], -1))
        h = h.astype(jnp.float32)
        prior_mean, prior_std = self._moments(self.transition_model(h))
        post_mean, post_std = self._moments(
            self.representation_model(jnp.concatenate([h, embed], -1))
        )
        z = post_mean + post_std * jax.random.normal(key, post_mean.shape)
        return (
            h,
            z,
            jnp.concatenate([post_mean, post_std], -1),
            jnp.concatenate([prior_mean, prior_std], -1),
        )

    def imagination(self, prev_h, prev_z, action, key):
        h = self.recurrent_model(prev_h, jnp.concatenate([prev_z, action], -1))
        h = h.astype(jnp.float32)
        prior_mean, prior_std = self._moments(self.transition_model(h))
        z = prior_mean + prior_std * jax.random.normal(key, prior_mean.shape)
        return h, z

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        return self.observation_model(latent)

    def reward_logits(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent)

    def continue_logits(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent)

    def __call__(self, obs, prev_h, prev_z, prev_action, is_first, key):
        embed = self.encode(obs)
        h, z, post, prior = self.dynamic(prev_h, prev_z, prev_action, embed, is_first, key)
        latent = jnp.concatenate([z, h], -1)
        recon = self.decode(latent)
        return h, z, post, prior, recon, self.reward_logits(latent), self.continue_logits(latent)


def build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state=None):
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    wm_cfg = cfg.algo.world_model
    cnn_shapes = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            shape = (shape[1], shape[2], shape[0] * shape[3])
        cnn_shapes[k] = tuple(shape)
    mlp_shapes = {k: int(np.prod(obs_space[k].shape)) for k in mlp_keys}
    dtype = fabric.precision.compute_dtype

    world_model = GaussianWorldModel(
        cnn_keys=cnn_keys, mlp_keys=mlp_keys, cnn_shapes=cnn_shapes, mlp_shapes=mlp_shapes,
        actions_dim=tuple(actions_dim),
        cnn_mult=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        recurrent_size=wm_cfg.recurrent_model.recurrent_state_size,
        hidden_size=wm_cfg.transition_model.hidden_size,
        stochastic_size=wm_cfg.stochastic_size,
        min_std=float(wm_cfg.min_std),
        act=cfg.algo.dense_act,
        dtype=dtype,
    )
    actor = Actor(
        actions_dim=tuple(actions_dim), is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units, mlp_layers=cfg.algo.actor.mlp_layers,
        act=cfg.algo.dense_act, layer_norm=False, unimix=0.0,
        min_std=cfg.algo.actor.min_std, init_std=cfg.algo.actor.init_std,
        action_clip=1.0, dtype=dtype,
    )
    critic = Critic(
        dense_units=cfg.algo.critic.dense_units, mlp_layers=cfg.algo.critic.mlp_layers,
        act=cfg.algo.dense_act, layer_norm=False, bins=1, dtype=dtype,
    )
    if state is not None:
        return world_model, actor, critic, fabric.replicate(state)

    key = jax.random.PRNGKey(cfg.seed)
    k_wm, k_actor, k_critic, k_s = jax.random.split(key, 4)
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, mlp_shapes[k]), jnp.float32)
    rec = wm_cfg.recurrent_model.recurrent_state_size
    wm_params = world_model.init(
        k_wm, dummy_obs, jnp.zeros((1, rec)), jnp.zeros((1, wm_cfg.stochastic_size)),
        jnp.zeros((1, int(sum(actions_dim)))), jnp.ones((1, 1)), k_s,
    )
    latent = jnp.zeros((1, wm_cfg.stochastic_size + rec))
    params = {
        "world_model": wm_params,
        "actor": actor.init(k_actor, latent),
        "critic": critic.init(k_critic, latent),
    }
    return world_model, actor, critic, fabric.replicate(params)
