"""DreamerV1 world-model loss, pure jittable math
(reference: sheeprl/algos/dreamer_v1/loss.py:41-95).

Deliberate deviation, stated plainly: the reference adds
``+continue_scale_factor * qc.log_prob(targets)`` to its reconstruction loss
(loss.py:93 — a positive log-likelihood term, which REWARDS a worse continue
head); this implementation uses the standard negative log-likelihood.  The
reference ships ``use_continues: False`` for DV1 (configs/algo/dreamer_v1.yaml:37),
so the default training path is identical.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.distribution import Normal, kl_normal


def reconstruction_loss(
    obs_nll: jax.Array,
    reward_nll: jax.Array,
    continue_nll: Optional[jax.Array],
    post_mean: jax.Array,
    post_std: jax.Array,
    prior_mean: jax.Array,
    prior_std: jax.Array,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """``obs_nll``/``reward_nll``/``continue_nll`` are per-step negative
    log-likelihoods of shape (L, B) (``continue_nll`` already scaled by the
    continue scale factor, or None when the continue head is disabled);
    posterior/prior are diagonal Gaussians over the stochastic state."""
    if continue_nll is None:
        continue_nll = jnp.zeros_like(reward_nll)
    kl = kl_normal(
        Normal(post_mean, post_std, event_dims=1), Normal(prior_mean, prior_std, event_dims=1)
    )
    state_loss = jnp.maximum(kl.mean(), kl_free_nats)
    total = kl_regularizer * state_loss + (obs_nll + reward_nll + continue_nll).mean()
    aux = {
        "kl": kl.mean(),
        "kl_loss": state_loss,
        "observation_loss": obs_nll.mean(),
        "reward_loss": reward_nll.mean(),
        "continue_loss": continue_nll.mean(),
    }
    return total, aux
