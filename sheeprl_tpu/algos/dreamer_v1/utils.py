"""DreamerV1 utilities (reference: sheeprl/algos/dreamer_v1/utils.py)."""

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}
