"""DreamerV1 — continuous-latent world-model RL
(reference: sheeprl/algos/dreamer_v1/dreamer_v1.py:1-750, loss.py:41-95).

World model: Gaussian RSSM trained with Gaussian reconstruction/reward NLL
plus plain KL to the prior with free nats 3.0.  Behavior: value network
trained on TD(λ) targets, actor maximizing λ-returns purely by dynamics
backprop (no REINFORCE term, no target networks, no return normalization).

Uses the shared Dreamer family loop and module stack (see dreamer_v1/agent).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import GaussianWorldModel, build_agent
from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values, normalize_obs_block
from sheeprl_tpu.algos.p2e_utils import ensemble_disagreement
from sheeprl_tpu.utils.distribution import Bernoulli, Normal
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import window_scan


def make_train_phase(fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
                     cnn_keys, mlp_keys, is_continuous, p2e=None, params=None, opt_state=None):
    # ``p2e``: optional Plan2Explore hook {ens_module, ens_opt, n, multiplier}
    # — trains the forward-model ensembles alongside the world model and runs
    # TWO behavior updates per step: the exploration actor + its own critic on
    # the pure ensemble-disagreement intrinsic reward, and the task actor +
    # task critic on extrinsic rewards (reference:
    # sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py:207-330 trains
    # actor_exploration/critic_exploration on intrinsic and
    # actor_task/critic_task on extrinsic — not a mixed reward).
    obs_keys = tuple(cnn_keys) + tuple(mlp_keys)
    stoch = world_model.stoch_flat
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    use_continues = bool(cfg.algo.world_model.use_continues)
    continue_scale = float(cfg.algo.world_model.continue_scale_factor)
    WM = GaussianWorldModel

    remat = bool(cfg.algo.get("remat", False))

    def maybe_remat(f):
        return jax.checkpoint(f) if remat else f

    def wm_forward(wm_params, data, k):
        L, B = data["rewards"].shape
        obs = normalize_obs_block(data, cnn_keys, obs_keys)
        flat_obs = {kk: v.reshape((L * B,) + v.shape[2:]) for kk, v in obs.items()}
        embed = world_model.apply(wm_params, flat_obs, method=WM.encode).reshape(L, B, -1)
        actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)
        is_first = data["is_first"].at[0].set(1.0)[..., None]

        def step(carry, xs):
            h, z = carry
            embed_t, act_t, first_t, k_t = xs
            h, z, post, prior = world_model.apply(
                wm_params, h, z, act_t, embed_t, first_t, k_t, method=WM.dynamic
            )
            return (h, z), (h, z, post, prior)

        keys = jax.random.split(k, L)
        _, (hs, zs, post_m, prior_m) = jax.lax.scan(
            maybe_remat(step), (jnp.zeros((B, rec_size)), jnp.zeros((B, stoch))),
            (embed, actions, is_first, keys),
        )
        latents = jnp.concatenate([zs, hs], -1)
        flat_latents = latents.reshape(L * B, -1)

        recon = world_model.apply(wm_params, flat_latents, method=WM.decode)
        obs_loss = 0.0
        for kk in cnn_keys:
            obs_loss = obs_loss - Normal(recon[kk].reshape(obs[kk].shape), 1.0, event_dims=3).log_prob(obs[kk])
        for kk in mlp_keys:
            obs_loss = obs_loss - Normal(recon[kk].reshape(L, B, -1), 1.0, event_dims=1).log_prob(obs[kk])

        reward_mean = world_model.apply(wm_params, flat_latents, method=WM.reward_logits)
        reward_loss = -Normal(reward_mean.reshape(L, B), 1.0).log_prob(data["rewards"])

        if use_continues:
            cont_logits = world_model.apply(wm_params, flat_latents, method=WM.continue_logits)
            continue_loss = -continue_scale * Bernoulli(cont_logits.reshape(L, B)).log_prob(
                (1.0 - data["terminated"]) * gamma
            )
        else:
            continue_loss = None

        post_mean, post_std = jnp.split(post_m, 2, -1)
        prior_mean, prior_std = jnp.split(prior_m, 2, -1)
        total, aux = reconstruction_loss(
            obs_loss, reward_loss, continue_loss, post_mean, post_std, prior_mean, prior_std,
            kl_free_nats=kl_free_nats, kl_regularizer=kl_regularizer,
        )
        aux["latents"] = latents
        return total, aux

    def behavior_update(p, o_state, latents, terminated, k,
                        actor_key="actor", critic_key="critic", reward_kind="extrinsic"):
        L, B = terminated.shape
        n = L * B
        start_latents = jax.lax.stop_gradient(latents.reshape(n, -1))

        def actor_loss_fn(actor_params):
            def img_step(carry, k_t):
                h, z = carry
                latent = jnp.concatenate([z, h], -1)
                k_a, k_z = jax.random.split(k_t)
                head = actor.apply(actor_params, latent)  # grads flow via dynamics
                action = actor.sample(head, k_a)
                h, z = world_model.apply(p["world_model"], h, z, action, k_z, method=WM.imagination)
                return (h, z), (latent, action)

            keys = jax.random.split(k, horizon + 1)
            _, (traj, actions_seq) = jax.lax.scan(
                maybe_remat(img_step), (start_latents[:, stoch:], start_latents[:, :stoch]), keys
            )
            flat_traj = traj.reshape((horizon + 1) * n, -1)
            if reward_kind == "intrinsic":
                # ensemble disagreement over next-state predictions
                preds = p2e["ens_module"].apply(
                    p["ensembles"],
                    jax.lax.stop_gradient(
                        jnp.concatenate([traj, actions_seq], -1)
                    ).reshape((horizon + 1) * n, -1),
                )
                rewards = ensemble_disagreement(
                    preds.reshape(p2e["n"], horizon + 1, n, -1), p2e["multiplier"]
                )
            else:
                rewards = world_model.apply(
                    p["world_model"], flat_traj, method=WM.reward_logits
                ).reshape(horizon + 1, n)
            values = critic.apply(p[critic_key], flat_traj).reshape(horizon + 1, n)
            if use_continues:
                continues = (
                    Bernoulli(
                        world_model.apply(p["world_model"], flat_traj, method=WM.continue_logits)
                        .reshape(horizon + 1, n)
                    ).mean
                    / gamma
                )
                true_continue = (1.0 - terminated).reshape(1, n)
                continues = jnp.concatenate([true_continue, continues[1:]], 0)
            else:
                continues = jnp.ones((horizon + 1, n))

            lambda_values = compute_lambda_values(
                rewards[1:], values[1:], continues[1:] * gamma, lmbda
            )
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, 0) / gamma)
            # pure dynamics backprop: maximize λ-returns (Eq. 7 of Dreamer)
            policy_loss = -jnp.mean(discount[:-1] * lambda_values)
            return policy_loss, (traj, lambda_values, discount)

        (pl, (traj, lambda_values, discount)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(p[actor_key])
        a_updates, new_a_opt = actor_opt.update(a_grads, o_state[actor_key], p[actor_key])
        p = {**p, actor_key: optax.apply_updates(p[actor_key], a_updates)}

        traj_sg = jax.lax.stop_gradient(traj[:-1])
        flat_sg = traj_sg.reshape(horizon * traj_sg.shape[1], -1)

        def critic_loss_fn(critic_params):
            qv = Normal(critic.apply(critic_params, flat_sg).reshape(horizon, -1), 1.0)
            return -jnp.mean(qv.log_prob(jax.lax.stop_gradient(lambda_values)) * discount[:-1])

        vl, c_grads = jax.value_and_grad(critic_loss_fn)(p[critic_key])
        c_updates, new_c_opt = critic_opt.update(c_grads, o_state[critic_key], p[critic_key])
        p = {**p, critic_key: optax.apply_updates(p[critic_key], c_updates)}
        return p, {**o_state, actor_key: new_a_opt, critic_key: new_c_opt}, pl, vl

    def single_update(carry, inputs):
        p, o_state, counter = carry
        data, k = inputs
        k_wm, k_beh, k_task = jax.random.split(k, 3)
        (wm_l, aux), wm_grads = jax.value_and_grad(wm_forward, has_aux=True)(
            p["world_model"], data, k_wm
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, o_state["world_model"], p["world_model"])
        p = {**p, "world_model": optax.apply_updates(p["world_model"], wm_updates)}
        o_state = {**o_state, "world_model": new_wm_opt}
        if p2e is not None:
            L, B = data["rewards"].shape
            latents = aux["latents"]

            def ens_loss(ep):
                inp = jax.lax.stop_gradient(
                    jnp.concatenate([latents, data["actions"]], -1)
                )[:-1].reshape((L - 1) * B, -1)
                preds = p2e["ens_module"].apply(ep, inp)
                target = jax.lax.stop_gradient(latents[1:, :, : world_model.stoch_flat])
                return jnp.mean(
                    (preds.reshape(p2e["n"], L - 1, B, -1) - target[None]) ** 2
                )

            el, e_grads = jax.value_and_grad(ens_loss)(p["ensembles"])
            e_updates, new_e_opt = p2e["ens_opt"].update(e_grads, o_state["ensembles"], p["ensembles"])
            p = {**p, "ensembles": optax.apply_updates(p["ensembles"], e_updates)}
            o_state = {**o_state, "ensembles": new_e_opt}
        if p2e is not None:
            # exploration policy ("actor" — the one the player acts with)
            # learns the intrinsic return; the task policy learns extrinsic
            p, o_state, pl_e, vl_e = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_beh,
                actor_key="actor", critic_key="critic_exploration", reward_kind="intrinsic",
            )
            p, o_state, pl_t, vl_t = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_task,
                actor_key="actor_task", critic_key="critic", reward_kind="extrinsic",
            )
            pl, vl = pl_e + pl_t, vl_e + vl_t
        else:
            p, o_state, pl, vl = behavior_update(
                p, o_state, aux["latents"], data["terminated"], k_beh
            )
        zero = jnp.zeros(())
        metrics = (
            wm_l, aux["observation_loss"], aux["reward_loss"], aux["kl_loss"],
            aux["continue_loss"], aux["kl"], pl, vl, zero, zero,
        )
        return (p, o_state, counter + 1), metrics

    def train_phase(p, o_state, blocks, k, counter0):
        U = blocks["rewards"].shape[0]
        keys = jax.random.split(k, U)
        (p, o_state, _), metrics = window_scan(
            single_update, (p, o_state, counter0), (blocks, keys), unroll=bool(cnn_keys)
        )
        return p, o_state, jax.tree.map(lambda x: x.mean(), metrics)

    in_sh = out_sh = None
    if params is not None and opt_state is not None:
        from sheeprl_tpu.parallel.compile import state_io_shardings
        from sheeprl_tpu.parallel.sharding import shardings_of

        in_sh, out_sh = state_io_shardings(
            shardings_of(params), shardings_of(opt_state), n_extra_in=3, n_extra_out=1
        )
    return fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1),
        in_shardings=in_sh,
        out_shardings=out_sh,
        max_recompiles=cfg.algo.get("max_recompiles"),
    )


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    dreamer_family_loop(fabric, cfg, build_agent, make_train_phase)
