"""Recurrent PPO (LSTM) — coupled topology.

Capability parity with the reference
(reference: sheeprl/algos/ppo_recurrent/ppo_recurrent.py:119-524): LSTM
policy over sequences, previous-action conditioning, recurrent-state reset
on episode start, sequence-wise minibatching.

TPU-native differences:
* the reference splits rollouts at episode bounds and pads minibatches of
  variable-length sequences (reference: agent.py:237-263); here episodes
  reset INSIDE the ``lax.scan`` via the ``is_first`` mask, so training
  consumes fixed ``(T, B)`` blocks with fully static shapes — minibatches
  are subsets of the env axis;
* the whole optimization phase (forward scan, GAE, epochs × env-minibatch
  updates) is one jitted dispatch, as in the other algorithms here.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

import optax

from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import actions_for_env, normalize_obs_keys, spaces_to_dims
from sheeprl_tpu.algos.ppo_recurrent.agent import (
    RecurrentPPOAgent,
    build_agent,
    one_hot_actions,
)
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import stage_rollout, stage_scalar, steady_guard
from sheeprl_tpu.utils.distribution import Categorical, Normal
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, normalize_tensor, polynomial_decay, save_configs


def _dist_stats(actor_out, actions, actions_dim, is_continuous):
    """Log-prob + entropy of given actions under the actor head output."""
    if is_continuous:
        mean, log_std = jnp.split(actor_out, 2, axis=-1)
        dist = Normal(mean, jnp.exp(jnp.clip(log_std, -10.0, 2.0)), event_dims=1)
        return dist.log_prob(actions), dist.entropy()
    lp, ent, start = 0.0, 0.0, 0
    for i, d in enumerate(actions_dim):
        dist = Categorical(actor_out[..., start:start + d])
        lp = lp + dist.log_prob(actions[..., i])
        ent = ent + dist.entropy()
        start += d
    return lp, ent


def _sample(actor_out, actions_dim, is_continuous, key, greedy=False):
    if is_continuous:
        mean, log_std = jnp.split(actor_out, 2, axis=-1)
        dist = Normal(mean, jnp.exp(jnp.clip(log_std, -10.0, 2.0)), event_dims=1)
        a = dist.mode() if greedy else dist.sample(key)
        return a, dist.log_prob(a)
    keys = jax.random.split(key, len(actions_dim))
    acts, lp, start = [], 0.0, 0
    for i, d in enumerate(actions_dim):
        dist = Categorical(actor_out[..., start:start + d])
        a = dist.mode() if greedy else dist.sample(keys[i])
        acts.append(a)
        lp = lp + dist.log_prob(a)
        start += d
    return jnp.stack(acts, axis=-1).astype(jnp.float32), lp


@register_algorithm()
def main(fabric: Any, cfg: Any) -> None:
    if cfg.buffer.get("share_data", False):
        import warnings

        warnings.warn(
            "buffer.share_data=True: with recurrent PPO only gradients are "
            "shared — per-env hidden-state sequences stay on their process "
            "(reference: sheeprl/algos/ppo_recurrent/ppo_recurrent.py:132-135)"
        )
    rank = fabric.global_rank
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    num_envs = cfg.env.num_envs
    from sheeprl_tpu.envs.jax.registry import anakin_enabled

    use_anakin = anakin_enabled(cfg, fabric)
    if use_anakin:
        # Anakin mode (envs/jax/anakin.py): the env lives INSIDE the
        # compiled update — no vector-env processes exist at all
        from sheeprl_tpu.envs.jax.core import VectorJaxEnv
        from sheeprl_tpu.envs.jax.registry import jax_env_from_cfg

        envs = None
        venv = VectorJaxEnv(jax_env_from_cfg(cfg), num_envs)
        obs_space = venv.single_observation_space
        act_space = venv.single_action_space
    else:
        envs = vectorize(
            cfg,
            [
                make_env(cfg, cfg.seed + rank * num_envs + i, rank, run_name=log_dir, vector_env_idx=i)
                for i in range(num_envs)
            ],
        )
        obs_space = envs.single_observation_space
        act_space = envs.single_action_space
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    act_width = int(sum(actions_dim))

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        # resume the train-dispatch RNG stream bit-exactly (rank-identical)
        key = jnp.asarray(state["key"])
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state.get("agent"))
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    opt_state = fabric.replicate(state.get("opt_state") or optimizer.init(params))

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)

    # on-policy loops honor algo.player.device (placement only; the sync
    # cadence options are meaningless on-policy: rollouts must use the
    # current weights)
    host = fabric.player_device(cfg)
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    vf_coef = float(cfg.algo.vf_coef)
    initial_ent_coef = float(cfg.algo.ent_coef)
    ent_coef_v = initial_ent_coef
    clip_coef = float(cfg.algo.clip_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    base_lr = float(cfg.algo.optimizer.lr)
    reduction = cfg.algo.loss_reduction
    update_epochs = int(cfg.algo.update_epochs)

    def policy_step_fn(p, carry, obs, prev_actions, is_first, k):
        # key advances INSIDE the jitted step (one host dispatch per env step)
        k_sample, k_next = jax.random.split(k)
        carry, (actor_out, value) = agent.apply(
            p, method=RecurrentPPOAgent.step, carry=carry, obs=obs,
            prev_actions=prev_actions, is_first=is_first,
        )
        actions, logprob = _sample(actor_out, actions_dim, is_continuous, k_sample)
        return carry, actions, logprob, value[..., 0], k_next

    # compile-once routing: AOT-compiled per abstract signature, counted by
    # the recompile detector (parallel/compile.py)
    policy_step_fn = fabric.compile(
        policy_step_fn,
        name=f"{cfg.algo.name}.policy_step",
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    def train_phase(p, o_state, rollout, init_carry, last_values, k, ent_coef, env_bs, num_minibatches):
        """Forward scan + GAE + epochs of env-axis minibatch updates."""
        T, B = rollout["rewards"].shape

        def fwd(p, env_idx):
            obs = {kk: jnp.take(rollout[kk], env_idx, axis=1) for kk in mlp_keys}
            prev_a = jnp.take(rollout["prev_actions"], env_idx, axis=1)
            first = jnp.take(rollout["is_first"], env_idx, axis=1)
            carry = (
                jnp.take(init_carry[0], env_idx, axis=0),
                jnp.take(init_carry[1], env_idx, axis=0),
            )
            return agent.apply(p, obs, prev_a, first, carry)

        all_idx = jnp.arange(B)
        actor_out, values = fwd(p, all_idx)
        values = values[..., 0]
        returns, advantages = gae(
            rollout["rewards"], values, rollout["dones"], last_values, gamma, gae_lambda
        )

        def epoch_body(carry, key_e):
            p, o_state = carry
            perm = jax.random.permutation(key_e, B)
            pad = num_minibatches * env_bs - B
            perm = jnp.concatenate([perm, perm[: max(pad, 0)]]) if pad > 0 else perm

            def mb_body(i, carry2):
                p, o_state, _ = carry2
                env_idx = jax.lax.dynamic_slice(perm, (i * env_bs,), (env_bs,))

                def loss_of(p_):
                    a_out, new_values = fwd(p_, env_idx)
                    acts = jnp.take(rollout["actions"], env_idx, axis=1)
                    lp, ent = _dist_stats(a_out, acts, actions_dim, is_continuous)
                    adv = jnp.take(advantages, env_idx, axis=1)
                    if normalize_adv:
                        adv = normalize_tensor(adv)
                    old_lp = jnp.take(rollout["logprobs"], env_idx, axis=1)
                    ret = jnp.take(returns, env_idx, axis=1)
                    old_v = jnp.take(values, env_idx, axis=1)
                    pg = policy_loss(lp, old_lp, adv, clip_coef, reduction)
                    vl = value_loss(new_values[..., 0], old_v, ret, clip_coef, clip_vloss, reduction)
                    el = entropy_loss(ent, reduction)
                    return pg + vf_coef * vl + ent_coef * el, (pg, vl, el)

                (_, (pg, vl, el)), grads = jax.value_and_grad(loss_of, has_aux=True)(p)
                updates, o_state = optimizer.update(grads, o_state, p)
                p = optax.apply_updates(p, updates)
                return p, o_state, (pg, vl, el)

            p, o_state, losses = jax.lax.fori_loop(
                0, num_minibatches, mb_body,
                (p, o_state, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))),
            )
            return (p, o_state), losses

        # recurrent PPO is MLP-only (no conv trunk): the XLA-CPU
        # outlined-loop penalty is conv-specific (utils.window_scan), so the
        # compact scan/fori lowering stays unconditionally
        (p, o_state), losses = jax.lax.scan(
            epoch_body, (p, o_state), jax.random.split(k, update_epochs)
        )
        return p, o_state, jax.tree.map(lambda x: x[-1], losses)

    # the staged rollout is donated too (argnum 2): one dispatch consumes it
    # exactly once (see ppo.py)
    train_phase_fn = train_phase  # raw callable: the Anakin path fuses it
    train_phase = fabric.compile(
        train_phase,
        name=f"{cfg.algo.name}.train_phase",
        donate_argnums=(0, 1, 2),
        static_argnames=("env_bs", "num_minibatches"),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )
    guard_on = bool(cfg.buffer.get("transfer_guard", False))

    # ---------------- counters ----------------------------------------------
    rollout_steps = int(cfg.algo.rollout_steps)
    # GLOBAL env-step accounting: every process steps its own envs
    policy_steps_per_iter = num_envs * rollout_steps * fabric.num_processes
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))

    rb = ReplayBuffer(rollout_steps, num_envs, memmap=False, obs_keys=mlp_keys) if not use_anakin else None

    hidden_size = int(cfg.algo.rnn.lstm.hidden_size)
    if not use_anakin:
        # rank-offset: each process's envs must be distinct streams or
        # multi-host DP collects the same data num_processes times
        obs, _ = envs.reset(seed=cfg.seed + rank * num_envs)
        prev_actions = np.zeros((num_envs, act_width), np.float32)
        is_first = np.ones((num_envs, 1), np.float32)
        carry_np = (
            np.zeros((num_envs, hidden_size), np.float32),
            np.zeros((num_envs, hidden_size), np.float32),
        )
    player_params = fabric.to_host(params)
    last_losses = None
    # per-rank player key stream, advanced inside policy_step_fn; the main
    # `key` stays rank-identical for train dispatches
    player_key = jax.device_put(
        # resume this rank's player RNG stream bit-exactly when saved
        jnp.asarray(state["player_key"]) if state and state.get("player_key") is not None
        else jax.random.fold_in(key, rank),
        host,
    )

    # the train phase is a GLOBAL program: under multi-host the env axis is
    # the concatenation of every process's local envs.  Single-process keeps
    # the replicated layout (env-axis minibatch gathers are cheapest there),
    # so sharding kicks in only across processes.
    sharded_envs = fabric.num_processes > 1
    if sharded_envs:
        fabric.env_sharding_plan(num_envs, "recurrent PPO")  # fail fast
    global_envs = num_envs * (fabric.num_processes if sharded_envs else 1)
    env_bs = max(
        1,
        min(global_envs, (int(cfg.algo.per_rank_batch_size) * fabric.world_size) // rollout_steps),
    )
    num_minibatches = -(-global_envs // env_bs)

    # ---------------- Anakin fused rollout+train ----------------------------
    if use_anakin:
        from sheeprl_tpu.envs.jax.anakin import (
            init_actor_state,
            make_recurrent_rollout_fn,
            traced_polynomial_decay,
        )

        def step_apply(p, carry, obs_d, prev_a, first):
            return agent.apply(
                p, method=RecurrentPPOAgent.step, carry=carry, obs=obs_d,
                prev_actions=prev_a, is_first=first,
            )

        def _sample_fn(actor_out, k):
            return _sample(actor_out, actions_dim, is_continuous, k)

        def _encode(a):
            return one_hot_actions(a, actions_dim, is_continuous)

        rollout_fn = make_recurrent_rollout_fn(
            venv, step_apply, _sample_fn, _encode,
            mlp_keys=mlp_keys, action_space=act_space, gamma=gamma,
            rollout_steps=rollout_steps,
        )

        def anakin_phase(p, o_state, actor, k):
            """``nn.scan``-policy rollout + forward scan + GAE + epochs in
            ONE device program, schedules computed in-trace from the
            donated update counter (zero H2D in steady state — the
            ppo/a2c Anakin gates, ROADMAP item 5)."""
            k_roll, k_train, k_next = jax.random.split(k, 3)
            step0 = actor["update"]
            ent = (
                traced_polynomial_decay(step0, initial=initial_ent_coef, max_decay_steps=total_iters)
                if cfg.algo.anneal_ent_coef
                else jnp.float32(initial_ent_coef)
            )
            if cfg.algo.anneal_lr:
                o_state = set_learning_rate(
                    o_state,
                    traced_polynomial_decay(step0, initial=base_lr, max_decay_steps=total_iters),
                )
            actor, rollout, init_carry, last_values, stats = rollout_fn(p, actor, k_roll)
            p, o_state, losses = train_phase_fn(
                p, o_state, rollout, init_carry, last_values, k_train, ent,
                env_bs=env_bs, num_minibatches=num_minibatches,
            )
            return p, o_state, actor, k_next, losses, stats

        anakin_step = fabric.compile(
            anakin_phase,
            name=f"{cfg.algo.name}.anakin_phase",
            donate_argnums=(0, 1, 2),
            max_recompiles=cfg.algo.get("max_recompiles"),
        )
        actor_state = init_actor_state(
            fabric, venv, jax.random.fold_in(key, fabric.global_rank + 1),
            start_iter - 1,
            sharded=num_envs % fabric.local_world_size == 0,
            extra={
                "carry": (
                    jnp.zeros((num_envs, hidden_size), jnp.float32),
                    jnp.zeros((num_envs, hidden_size), jnp.float32),
                ),
                "prev_actions": jnp.zeros((num_envs, act_width), jnp.float32),
                "is_first": jnp.ones((num_envs, 1), jnp.float32),
            },
        )
    guard_anakin = bool(cfg.buffer.get("transfer_guard", False))

    for update in range(start_iter, total_iters + 1):
        if use_anakin:
            # -------- fused rollout+train: ONE dispatch per update ---------
            with timer("Time/train_time"):
                with steady_guard(guard_anakin and update > start_iter):
                    params, opt_state, actor_state, key, last_losses, ep_stats = anakin_step(
                        params, opt_state, actor_state, key
                    )
                policy_step += num_envs * rollout_steps * fabric.num_processes
            if cfg.metric.log_level > 0:
                # completion arrays are tiny; the pull is D2H (legal under
                # the H2D-scoped steady guard)
                from sheeprl_tpu.envs.jax.anakin import episode_stats_from_device

                rets, lens = episode_stats_from_device(ep_stats)
                for ep_ret, ep_len in zip(rets, lens):
                    aggregator.update("Rewards/rew_avg", float(ep_ret))
                    aggregator.update("Game/ep_len_avg", int(ep_len))
        else:
            init_carry = (carry_np[0].copy(), carry_np[1].copy())
            with timer("Time/env_interaction_time"):
                with jax.default_device(host):
                    for _ in range(rollout_steps):
                        policy_step += num_envs * fabric.num_processes
                        dev_obs = {
                            k: jnp.asarray(np.asarray(obs[k], np.float32).reshape(num_envs, -1))
                            for k in mlp_keys
                        }
                        carry, actions, logprobs, _, player_key = policy_step_fn(
                            player_params,
                            (jnp.asarray(carry_np[0]), jnp.asarray(carry_np[1])),
                            dev_obs,
                            jnp.asarray(prev_actions),
                            jnp.asarray(is_first),
                            player_key,
                        )
                        carry_np = (np.asarray(carry[0]), np.asarray(carry[1]))
                        actions_np = np.asarray(actions)
                        next_obs, rewards, terminated, truncated, info = envs.step(
                            actions_for_env(actions_np, act_space)
                        )
                        dones = np.logical_or(terminated, truncated).astype(np.float32)
                        rewards = np.asarray(rewards, np.float32)

                        # truncation bootstrap (reference: ppo.py:287-306) using the
                        # post-step recurrent state; padded to the full env batch
                        if np.any(truncated):
                            final_obs = final_obs_rows(info, np.nonzero(truncated)[0], mlp_keys)
                            if final_obs is not None:
                                padded = {
                                    k: np.asarray(next_obs[k], np.float32).reshape(num_envs, -1).copy()
                                    for k in mlp_keys
                                }
                                for k in mlp_keys:
                                    padded[k][truncated] = np.asarray(final_obs[k], np.float32).reshape(
                                        int(truncated.sum()), -1
                                    )
                                prev_a_boot = np.asarray(
                                    one_hot_actions(jnp.asarray(actions_np), actions_dim, is_continuous)
                                )
                                _, (_, v_boot) = agent.apply(
                                    player_params, method=RecurrentPPOAgent.step,
                                    carry=(jnp.asarray(carry_np[0]), jnp.asarray(carry_np[1])),
                                    obs={k: jnp.asarray(padded[k]) for k in mlp_keys},
                                    prev_actions=jnp.asarray(prev_a_boot),
                                    is_first=jnp.zeros((num_envs, 1)),
                                )
                                v_boot = np.asarray(v_boot)[..., 0]
                                rewards[truncated] += gamma * v_boot[truncated]

                        step = {
                            "actions": actions_np[None],
                            "logprobs": np.asarray(logprobs)[None],
                            "rewards": rewards[None],
                            "dones": dones[None],
                            "is_first": is_first[None, :, 0],
                            "prev_actions": prev_actions[None],
                        }
                        for k in mlp_keys:
                            step[k] = np.asarray(obs[k], np.float32).reshape(1, num_envs, -1)
                        rb.add({k: v[..., None] if v.ndim == 2 else v for k, v in step.items()})

                        obs = next_obs
                        prev_actions = np.array(
                            one_hot_actions(jnp.asarray(actions_np), actions_dim, is_continuous)
                        )
                        prev_actions[dones.astype(bool)] = 0.0
                        is_first = dones[:, None]
                        for ep_ret, ep_len in episode_stats(info):
                            aggregator.update("Rewards/rew_avg", ep_ret)
                            aggregator.update("Game/ep_len_avg", ep_len)

            with timer("Time/train_time"):
                # donated device staging: host-numpy layout + EXPLICIT device_puts
                # (data/device_replay.stage_rollout), rollout donated into the
                # one-dispatch update (see ppo.py)
                local = rb.buffer
                host_rollout = {k: np.asarray(local[k], np.float32) for k in mlp_keys}
                host_rollout["actions"] = np.asarray(local["actions"])
                host_rollout["prev_actions"] = np.asarray(local["prev_actions"])
                host_rollout["logprobs"] = np.asarray(local["logprobs"][..., 0])
                host_rollout["rewards"] = np.asarray(local["rewards"][..., 0])
                host_rollout["dones"] = np.asarray(local["dones"][..., 0])
                host_rollout["is_first"] = np.asarray(local["is_first"])  # (T, B, 1)
                # single-process: replicate (the env-axis minibatch gathers are
                # cheapest on replicated data); multi-host: each process only has
                # its own env rows, so assemble the global env axis instead
                rollout = stage_rollout(fabric, host_rollout, axis=1, sharded=sharded_envs)

                # bootstrap values for the state after the rollout
                dev_obs = {
                    k: jnp.asarray(np.asarray(obs[k], np.float32).reshape(num_envs, -1)) for k in mlp_keys
                }
                _, (_, last_v) = agent.apply(
                    player_params, method=RecurrentPPOAgent.step,
                    carry=(jnp.asarray(carry_np[0]), jnp.asarray(carry_np[1])),
                    obs=dev_obs, prev_actions=jnp.asarray(prev_actions),
                    is_first=jnp.asarray(is_first),
                )
                key, tk = jax.random.split(key)
                carry_pair = (np.asarray(init_carry[0]), np.asarray(init_carry[1]))
                last_v_flat = np.asarray(last_v)[..., 0]
                ent_dev = stage_scalar(ent_coef_v)
                with steady_guard(guard_on and update > start_iter):
                    params, opt_state, last_losses = train_phase(
                        params, opt_state, rollout,
                        fabric.shard_batch(carry_pair, axis=0) if sharded_envs else fabric.replicate(carry_pair),
                        fabric.shard_batch(last_v_flat, axis=0) if sharded_envs else fabric.replicate(last_v_flat),
                        tk, ent_dev, env_bs=env_bs, num_minibatches=num_minibatches,
                    )
                player_params = fabric.to_host(params)

        # (Anakin mode anneals in-trace from the donated update counter —
        # host-side schedule state would be a per-update H2D transfer)
        if cfg.algo.anneal_lr and not use_anakin:
            opt_state = set_learning_rate(
                opt_state,
                polynomial_decay(update, initial=base_lr, final=0.0, max_decay_steps=total_iters),
            )
        if cfg.algo.anneal_ent_coef and not use_anakin:
            ent_coef_v = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
        ):
            if last_losses is not None:
                pg, vl, el = last_losses
                aggregator.update("Loss/policy_loss", pg)
                aggregator.update("Loss/value_loss", vl)
                aggregator.update("Loss/entropy_loss", el)
            last_log = flush_metrics(aggregator, timer, logger, policy_step, last_log)

        if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "player_key": player_key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt"),
                state=ckpt_state,
            )
        if ckpt_mgr.preempted:
            fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
            break

    if envs is not None:
        envs.close()
    ckpt_mgr.finalize()
    if fabric.is_global_zero and cfg.algo.run_test and not ckpt_mgr.preempted:
        from sheeprl_tpu.algos.ppo_recurrent.utils import test

        if use_anakin:
            # the fused path never maintained a host player copy
            player_params = fabric.to_host(params)
        test(agent, player_params, cfg, log_dir, logger)
    if logger is not None:
        logger.close()
