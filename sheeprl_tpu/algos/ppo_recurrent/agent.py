"""Recurrent PPO agent (flax LSTM).

Capability parity with the reference agent
(reference: sheeprl/algos/ppo_recurrent/agent.py:18-470): feature MLP over
observations concatenated with one-hot previous actions, optional pre/post
RNN projections, an LSTM whose state carries across steps, and actor/critic
heads on the LSTM output.

TPU-first: the time loop is ALWAYS a ``lax.scan`` over the fused step
function, with the done-mask resetting the carried state inside the scan —
so training consumes full ``(T, B)`` rollouts with static shapes and needs
none of the reference's per-episode splitting/padding machinery
(reference: agent.py:237-263).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from sheeprl_tpu.models.models import MLP


class RecurrentPPOAgent(nn.Module):
    actions_dim: Tuple[int, ...]
    is_continuous: bool
    mlp_keys: Tuple[str, ...]
    encoder_units: int
    mlp_layers: int
    dense_act: str
    layer_norm: bool
    lstm_size: int
    pre_rnn: Dict[str, Any]
    post_rnn: Dict[str, Any]
    actor_cfg: Dict[str, Any]
    critic_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    def setup(self) -> None:
        self.encoder = MLP(
            hidden_sizes=(self.encoder_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="encoder",
        )
        if self.pre_rnn.get("apply"):
            self.pre_mlp = MLP(
                hidden_sizes=(self.pre_rnn["dense_units"],),
                activation=self.pre_rnn.get("activation", "relu"),
                layer_norm=self.pre_rnn.get("layer_norm", False),
                dtype=self.dtype,
                name="pre_rnn_mlp",
            )
        if self.post_rnn.get("apply"):
            self.post_mlp = MLP(
                hidden_sizes=(self.post_rnn["dense_units"],),
                activation=self.post_rnn.get("activation", "relu"),
                layer_norm=self.post_rnn.get("layer_norm", False),
                dtype=self.dtype,
                name="post_rnn_mlp",
            )
        self.cell = nn.OptimizedLSTMCell(self.lstm_size, name="lstm")
        self.actor = MLP(
            hidden_sizes=(self.actor_cfg.get("dense_units", 64),) * self.actor_cfg.get("mlp_layers", 1),
            output_dim=sum(self.actions_dim) * (2 if self.is_continuous else 1),
            activation=self.actor_cfg.get("dense_act", "relu"),
            layer_norm=self.actor_cfg.get("layer_norm", False),
            dtype=self.dtype,
            name="actor",
        )
        self.critic = MLP(
            hidden_sizes=(self.critic_cfg.get("dense_units", 64),) * self.critic_cfg.get("mlp_layers", 1),
            output_dim=1,
            activation=self.critic_cfg.get("dense_act", "relu"),
            layer_norm=self.critic_cfg.get("layer_norm", False),
            dtype=self.dtype,
            name="critic",
        )

    def _features(self, obs: Dict[str, jax.Array], prev_actions: jax.Array) -> jax.Array:
        vec = jnp.concatenate([obs[k] for k in self.mlp_keys] + [prev_actions], axis=-1)
        x = self.encoder(vec)
        if self.pre_rnn.get("apply"):
            x = self.pre_mlp(x)
        return x

    def step(
        self,
        carry: Tuple[jax.Array, jax.Array],
        obs: Dict[str, jax.Array],
        prev_actions: jax.Array,
        is_first: jax.Array,
    ) -> Tuple[Tuple[jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]:
        """One recurrent step for a ``(B, ...)`` batch; ``is_first`` (B, 1)
        zeroes the carried state at episode starts
        (``reset_recurrent_state_on_done`` semantics)."""
        c, h = carry
        mask = 1.0 - is_first
        c, h = c * mask, h * mask
        x = self._features(obs, prev_actions)
        (c, h), out = self.cell((c, h), x)
        if self.post_rnn.get("apply"):
            out = self.post_mlp(out)
        actor_out = self.actor(out).astype(jnp.float32)
        value = self.critic(out).astype(jnp.float32)
        return (c, h), (actor_out, value)

    def __call__(
        self,
        obs_seq: Dict[str, jax.Array],
        prev_actions_seq: jax.Array,
        is_first_seq: jax.Array,
        initial_state: Tuple[jax.Array, jax.Array],
    ) -> Tuple[jax.Array, jax.Array]:
        """Scan over a ``(T, B, ...)`` sequence; returns (T, B, ·) heads.

        The time loop is flax's LIFTED scan: a raw ``jax.lax.scan`` over a
        bound method trips linen's trace-level check (JaxTransformError —
        submodule access from inside a jax transform); ``nn.scan`` with
        ``variable_broadcast='params'`` shares the step's parameters across
        the unrolled time axis, which is exactly the recurrent semantics."""

        def body(mdl: "RecurrentPPOAgent", carry, xs):
            obs_t, act_t, first_t = xs
            return mdl.step(carry, obs_t, act_t, first_t)

        scan = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        _, (actor_out, values) = scan(
            self, initial_state, (obs_seq, prev_actions_seq, is_first_seq)
        )
        return actor_out, values

    def initial_state(self, batch: int) -> Tuple[jax.Array, jax.Array]:
        return (
            jnp.zeros((batch, self.lstm_size), self.dtype),
            jnp.zeros((batch, self.lstm_size), self.dtype),
        )


def one_hot_actions(actions: jax.Array, actions_dim: Sequence[int], is_continuous: bool) -> jax.Array:
    """Encode stored actions for the next-step input: one-hot per discrete
    branch, identity for continuous (reference feeds prev actions likewise)."""
    if is_continuous:
        return actions
    parts = [
        jax.nn.one_hot(actions[..., i].astype(jnp.int32), d, dtype=jnp.float32)
        for i, d in enumerate(actions_dim)
    ]
    return jnp.concatenate(parts, axis=-1)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Any,
    obs_space: Any,
    agent_state: Optional[Any] = None,
) -> Tuple[RecurrentPPOAgent, Any]:
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    agent = RecurrentPPOAgent(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        mlp_keys=mlp_keys,
        encoder_units=cfg.algo.encoder.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        dense_act=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        lstm_size=cfg.algo.rnn.lstm.hidden_size,
        pre_rnn=dict(cfg.algo.rnn.pre_rnn_mlp),
        post_rnn=dict(cfg.algo.rnn.post_rnn_mlp),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
        dtype=fabric.precision.compute_dtype,
    )
    if agent_state is not None:
        return agent, fabric.replicate(agent_state)
    import numpy as np

    act_width = sum(actions_dim) if not is_continuous else int(sum(actions_dim))
    dummy_obs = {k: jnp.zeros((1, int(np.prod(obs_space[k].shape))), jnp.float32) for k in mlp_keys}
    params = agent.init(
        jax.random.PRNGKey(cfg.seed),
        method=RecurrentPPOAgent.step,
        carry=agent.initial_state(1),
        obs=dummy_obs,
        prev_actions=jnp.zeros((1, act_width), jnp.float32),
        is_first=jnp.ones((1, 1), jnp.float32),
    )
    return agent, fabric.replicate(params)


