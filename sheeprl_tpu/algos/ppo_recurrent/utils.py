"""Recurrent PPO utilities (reference: sheeprl/algos/ppo_recurrent/utils.py)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


def test(agent: Any, params: Any, cfg: Any, log_dir: str, logger: Any = None, greedy: bool = True) -> float:
    from sheeprl_tpu.algos.ppo.utils import actions_for_env, spaces_to_dims
    from sheeprl_tpu.algos.ppo_recurrent.agent import RecurrentPPOAgent, one_hot_actions
    from sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent import _sample
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, run_name=log_dir, prefix="test")()
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    actions_dim, is_continuous = spaces_to_dims(env.action_space)
    act_width = int(sum(actions_dim))
    hidden = cfg.algo.rnn.lstm.hidden_size

    @jax.jit
    def step(p, carry, o, prev_a, first, k):
        carry, (actor_out, _) = agent.apply(
            p, method=RecurrentPPOAgent.step, carry=carry, obs=o,
            prev_actions=prev_a, is_first=first,
        )
        a, _ = _sample(actor_out, actions_dim, is_continuous, k, greedy=greedy)
        return carry, a

    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    carry = (jnp.zeros((1, hidden)), jnp.zeros((1, hidden)))
    prev_a = jnp.zeros((1, act_width))
    first = jnp.ones((1, 1))
    done, cum_reward = False, 0.0
    while not done:
        o = {k: jnp.asarray(np.asarray(obs[k], np.float32).reshape(1, -1)) for k in mlp_keys}
        key, sk = jax.random.split(key)
        carry, a = step(params, carry, o, prev_a, first, sk)
        a_np = np.asarray(a)
        obs, reward, terminated, truncated, _ = env.step(actions_for_env(a_np, env.action_space)[0])
        done = bool(terminated or truncated)
        prev_a = one_hot_actions(a, actions_dim, is_continuous)
        first = jnp.zeros((1, 1))
        cum_reward += float(reward)
    env.close()
    if logger is not None:
        logger.log_metrics({"Test/cumulative_reward": cum_reward}, 0)
    return cum_reward
