"""Plan2Explore over DreamerV2 — exploration phase
(reference: sheeprl/algos/p2e_dv2/p2e_dv2_exploration.py).

An ensemble of N forward models is trained to predict the next stochastic
state from the current latent; its prediction variance is the intrinsic
reward, mixed into the imagined returns with configured weights while the
ensembles train alongside the world model.  Simplification vs the reference
(documented): a single actor/critic learns the MIXED intrinsic+extrinsic
return instead of the per-reward critic dict (the full dict lives in the
DV3 variant, sheeprl_tpu/algos/p2e_dv3).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import build_agent as base_build_agent
from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_train_phase as base_make_train_phase
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.registry import register_algorithm


def build_agent(fabric, actions_dim, is_continuous, cfg, obs_space, state=None):
    world_model, actor, critic, params = base_build_agent(
        fabric, actions_dim, is_continuous, cfg, obs_space, state
    )
    if state is not None:
        return world_model, actor, critic, params
    ens = _ensemble(cfg, world_model)
    rec = cfg.algo.world_model.recurrent_model.recurrent_state_size
    latent_dim = world_model.stoch_flat + rec + int(sum(actions_dim))
    ens_params = ens.init(jax.random.PRNGKey(cfg.seed + 1), jnp.zeros((1, latent_dim)))
    params = jax.device_get(params)
    params = {**params, "ensembles": ens_params}
    return world_model, actor, critic, fabric.replicate(params)


def _ensemble(cfg, world_model):
    import flax.linen as nn

    from sheeprl_tpu.algos.dreamer_v3.agent import DreamerMLP

    class Ensembles(nn.Module):
        @nn.compact
        def __call__(self, x):
            net = nn.vmap(
                DreamerMLP, in_axes=None, out_axes=0,
                axis_size=int(cfg.algo.ensembles.n),
                variable_axes={"params": 0}, split_rngs={"params": True},
            )
            return net(
                units=cfg.algo.ensembles.dense_units,
                layers=cfg.algo.ensembles.mlp_layers,
                output_dim=world_model.stoch_flat,
                act=cfg.algo.dense_act,
                layer_norm=False,
                name="ens",
            )(x)

    return Ensembles()


def make_train_phase(fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
                     cnn_keys, mlp_keys, is_continuous):
    p2e = {
        "ens_module": _ensemble(cfg, world_model),
        "ens_opt": build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients),
        "n": int(cfg.algo.ensembles.n),
        "w_intrinsic": float(cfg.algo.critics_exploration.intrinsic.weight),
        "w_extrinsic": float(cfg.algo.critics_exploration.extrinsic.weight),
        "multiplier": float(cfg.algo.intrinsic_reward_multiplier),
    }
    return base_make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys, mlp_keys, is_continuous, p2e=p2e,
    )


def build_optimizers(fabric, cfg, params, saved=None):
    wm_opt = build_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ens_opt = build_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)
    opt_state = fabric.replicate(
        saved
        or {
            "world_model": wm_opt.init(params["world_model"]),
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "ensembles": ens_opt.init(params["ensembles"]),
        }
    )
    return wm_opt, actor_opt, critic_opt, opt_state


@register_algorithm(name="p2e_dv2_exploration")
def main(fabric: Any, cfg: Any) -> None:
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import dreamer_family_loop

    dreamer_family_loop(
        fabric, cfg, build_agent, make_train_phase, optimizer_builder=build_optimizers
    )
