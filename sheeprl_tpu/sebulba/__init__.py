"""Sebulba pod-scale actor–learner runtime (Podracer, arXiv:2104.06272).

The decoupled algorithms route here when ``topology=sebulba`` resolves
(see :mod:`sheeprl_tpu.parallel.topology` and docs/sebulba.md): mesh
devices split into an actor group (batched AOT inference / fused jax-env
rollout shards) and a learner group (the training sub-mesh consuming a
device-resident trajectory queue), with learner→actor parameter flow as a
staleness-bounded device-to-device broadcast.
"""

from sheeprl_tpu.sebulba.actor import (  # noqa: F401
    ActorEngine,
    EnvWorker,
    FusedActor,
    WorkerSupervisor,
    derive_ladder,
)
from sheeprl_tpu.sebulba.queues import (  # noqa: F401
    ObsBlock,
    ObsQueue,
    TornTrajectory,
    TrajQueue,
)
