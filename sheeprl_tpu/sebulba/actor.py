"""Sebulba actor side: batched AOT inference engines + env-worker drivers.

One :class:`ActorEngine` runs per actor device (Sebulba co-locates an
inference engine with each actor core): a dispatcher thread coalesces env
workers' observation blocks off the shared :class:`~sheeprl_tpu.sebulba.
queues.ObsQueue` (serve-batcher max-batch/max-wait policy), pads the batch
up to a static **ladder** rung, and dispatches ONE AOT executable per rung
(``parallel/compile.py`` — each rung is its own compile-once program, so
every executable holds ``cache_size() == 1`` for the life of the run).

Env workers are lightweight *drivers*: each owns ``num_envs/env_workers``
envs through the standard ``utils.env.vectorize`` machinery (with
``env.sync_env=False`` the actual stepping runs in ``AsyncVectorEnv``
subprocesses), submits its observation block per step, and assembles
fixed-length trajectory segments that it pushes into the device-resident
:class:`~sheeprl_tpu.sebulba.queues.TrajQueue`.  Workers heartbeat a
:class:`~sheeprl_tpu.resilience.retry.Watchdog`; a crashed or hung worker
(the ``sebulba.env_worker`` fault site) is **deposed and respawned** with
fresh envs — a deposed worker can never push again, so partial segments
die with it and torn trajectories cannot reach the learner.

For pure-JAX envs the actor group skips the queue entirely:
:class:`FusedActor` runs an Anakin-style fused rollout shard per actor
device (the whole ``lax.scan`` rollout is one executable, H2D-free in
steady state) and ships finished segments device-to-device into the
trajectory queue.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from sheeprl_tpu.parallel.compile import AOTFunction, compile_once
from sheeprl_tpu.parallel.topology import ParamBroadcast
from sheeprl_tpu.resilience.faults import fault_point
from sheeprl_tpu.sebulba.queues import ObsBlock, ObsQueue, ServiceStopped, TrajQueue
from sheeprl_tpu.serve.batcher import pick_ladder_size


def derive_ladder(block_rows: int, max_blocks: int, override: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """The static batch ladder for actor inference: multiples of the
    per-worker block size in powers of two, topped by the full round
    (``block_rows * max_blocks``) so a fully-coalesced step pads nothing."""
    if override:
        ladder = sorted({int(b) for b in override})
        if any(b % block_rows for b in ladder):
            raise ValueError(
                f"topology.actor_batch_ladder {ladder} must be multiples of "
                f"the worker block size ({block_rows} rows)"
            )
        return tuple(ladder)
    sizes = set()
    b = block_rows
    while b < block_rows * max_blocks:
        sizes.add(b)
        b *= 2
    sizes.add(block_rows * max_blocks)
    return tuple(sorted(sizes))


class ActorEngine(threading.Thread):
    """One actor device's batched-inference dispatcher.

    ``policy_fn(params, obs, key) -> (outputs, key')`` is the algo's pure
    per-row policy (outputs: dict of row-major arrays).  Each ladder rung
    gets its OWN compile-once executable (``sebulba.actor_step[i]@rung``),
    warmed ahead of traffic via :meth:`warmup`; the dispatcher then only
    ever feeds data.  Params arrive by device-to-device broadcast
    (:class:`ParamBroadcast`); the PRNG key lives on the actor device and
    advances inside the executable, so a steady-state dispatch moves only
    the observation batch host→device (and nothing at all for device-fed
    observations).
    """

    def __init__(
        self,
        index: int,
        device: Any,
        policy_fn: Callable,
        obs_spec: Dict[str, Tuple[Tuple[int, ...], Any]],
        param_spec: Any,
        ladder: Sequence[int],
        block_rows: int,
        obs_queue: ObsQueue,
        broadcast: ParamBroadcast,
        key: jax.Array,
        *,
        max_wait_s: float = 0.02,
        max_recompiles: Optional[int] = None,
        name: str = "sebulba.actor",
    ):
        super().__init__(name=f"{name}[{index}]", daemon=True)
        self.index = int(index)
        self.device = device
        self.ladder = tuple(sorted(int(b) for b in ladder))
        self.block_rows = int(block_rows)
        self.obs_queue = obs_queue
        self.broadcast = broadcast
        self.max_wait_s = float(max_wait_s)
        self._obs_spec = dict(obs_spec)
        self._param_spec = param_spec
        self._key = jax.device_put(key, device)
        self._stop_event = threading.Event()
        self.error: Optional[BaseException] = None
        # observability
        self.dispatches = 0
        self.rows_served = 0
        self.rows_padded = 0
        self.idle_s = 0.0
        self.busy_s = 0.0
        self._started_at: Optional[float] = None

        # one compile-once program PER LADDER RUNG — "one executable per
        # batch-ladder size": each AOTFunction sees exactly one abstract
        # signature, so cache_size()==1 is the per-rung steady-state law
        self.executables: Dict[int, AOTFunction] = {
            rung: compile_once(
                policy_fn,
                name=f"sebulba.actor_step[{index}]@{rung}",
                max_recompiles=max_recompiles,
            )
            for rung in self.ladder
        }

    # -- warm-up --------------------------------------------------------------
    def _specs_for(self, rung: int) -> Tuple[Any, Any, Any]:
        from jax.sharding import SingleDeviceSharding

        sd = SingleDeviceSharding(self.device)
        obs = {
            k: jax.ShapeDtypeStruct((rung,) + tuple(shape), dtype, sharding=sd)
            for k, (shape, dtype) in self._obs_spec.items()
        }
        params = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd), self._param_spec
        )
        key = jax.ShapeDtypeStruct(self._key.shape, self._key.dtype, sharding=sd)
        return params, obs, key

    def warmup(self, pool: Any = None, join: bool = True) -> None:
        """AOT-compile every rung (concurrently on the compile pool) before
        traffic — steady state then never compiles."""
        from sheeprl_tpu.parallel.compile import get_compile_pool

        pool = pool or get_compile_pool()
        futures = [
            pool.submit(self.executables[rung], *self._specs_for(rung)) for rung in self.ladder
        ]
        if join:
            pool.join()
        return futures

    def cache_sizes(self) -> Dict[int, int]:
        return {rung: fn.cache_size() for rung, fn in self.executables.items()}

    # -- dispatch loop --------------------------------------------------------
    def stop(self) -> None:
        self._stop_event.set()

    def actor_idle_frac(self) -> float:
        total = self.idle_s + self.busy_s
        return self.idle_s / total if total > 0 else 0.0

    def _dispatch(self, blocks: List[ObsBlock]) -> None:
        rows = sum(b.rows for b in blocks)
        rung = pick_ladder_size(rows, self.ladder)
        batch: Dict[str, np.ndarray] = {}
        for k, (shape, dtype) in self._obs_spec.items():
            buf = np.zeros((rung,) + tuple(shape), dtype)
            at = 0
            for b in blocks:
                buf[at : at + b.rows] = b.obs[k]
                at += b.rows
            batch[k] = buf
        params, version = self.broadcast.fetch(self.index)
        dev_batch = jax.device_put(batch, self.device)
        outputs, self._key = self.executables[rung](params, dev_batch, self._key)
        outputs = {k: np.asarray(v) for k, v in outputs.items()}
        self.dispatches += 1
        self.rows_served += rows
        self.rows_padded += rung - rows
        at = 0
        for b in blocks:
            row_out = {k: v[at : at + b.rows] for k, v in outputs.items()}
            row_out["_version"] = version
            at += b.rows
            b.resolve(row_out)

    def run(self) -> None:
        self._started_at = time.perf_counter()
        max_blocks = max(self.ladder) // self.block_rows
        try:
            while not self._stop_event.is_set():
                t0 = time.perf_counter()
                blocks = self.obs_queue.get_batch(max_blocks, self.max_wait_s)
                self.idle_s += time.perf_counter() - t0
                blocks = [b for b in blocks if not b.cancelled]
                if not blocks:
                    if self.obs_queue.closed:
                        break
                    continue
                t1 = time.perf_counter()
                try:
                    self._dispatch(blocks)
                except BaseException as e:  # noqa: BLE001 — fail the callers, then re-raise
                    for b in blocks:
                        b.fail(e)
                    raise
                self.busy_s += time.perf_counter() - t1
        except BaseException as e:  # noqa: BLE001 — surfaced by the runner
            if not self._stop_event.is_set():
                self.error = e


class EnvWorker(threading.Thread):
    """One env-worker driver: steps its env slice, requests actions from
    the actor group, assembles fixed-length segments.

    ``protocol`` owns the algorithm-specific step semantics through one
    method::

        run_segment(infer, envs, obs, steps)
            -> (next_obs, segment_dict, episode_stats, env_steps)

    where ``infer(block) -> (outputs, version)`` round-trips one
    observation block through the actor group.  A worker whose
    :attr:`deposed` flag is set (the supervisor decided it is wedged)
    exits at the next boundary and never pushes a segment again.
    """

    def __init__(
        self,
        worker_id: int,
        env_builder: Callable[[], Any],
        protocol: Any,
        obs_queue: ObsQueue,
        traj_queue: TrajQueue,
        rollout_steps: int,
        seed: int,
        *,
        timeout_s: float = 300.0,
        stop_event: Optional[threading.Event] = None,
        stats_sink: Optional[Callable[[Sequence[Tuple[float, int]]], None]] = None,
        generation: int = 0,
    ):
        super().__init__(name=f"sebulba.env_worker[{worker_id}]g{generation}", daemon=True)
        self.worker_id = int(worker_id)
        self.env_builder = env_builder
        self.protocol = protocol
        self.obs_queue = obs_queue
        self.traj_queue = traj_queue
        self.rollout_steps = int(rollout_steps)
        self.seed = int(seed)
        self.timeout_s = float(timeout_s)
        self.stop_event = stop_event or threading.Event()
        self.stats_sink = stats_sink
        self.generation = int(generation)
        self.deposed = False
        self.error: Optional[BaseException] = None
        self.last_beat = time.monotonic()
        self.segments_pushed = 0
        self.env_steps = 0
        self._last_version = 0

    # -- actor round-trip -----------------------------------------------------
    def infer(self, block: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        rows = int(next(iter(block.values())).shape[0])
        req = ObsBlock(self.worker_id, block, rows)
        self.obs_queue.put(req, block=True, timeout=self.timeout_s)
        # wait in slices, touching the heartbeat: legitimately queueing
        # behind a slow actor dispatch is LIVENESS, not a hang — only a
        # worker that stops reaching this loop goes stale
        deadline = time.monotonic() + self.timeout_s
        while not req.event.wait(0.25):
            self.touch()
            if self.deposed:
                req.cancelled = True
                raise _Deposed()
            if time.monotonic() > deadline:
                req.cancelled = True
                raise TimeoutError("actor inference request timed out")
        if req.error is not None:
            raise req.error
        out = req.result
        self._last_version = int(out.get("_version", self._last_version))
        return out

    def touch(self) -> None:
        """Refresh the heartbeat WITHOUT the fault site (used from waits
        where the worker is blocked but healthy)."""
        self.last_beat = time.monotonic()

    def beat(self) -> None:
        self.last_beat = time.monotonic()
        # the sebulba.env_worker fault site fires per env step, from the
        # worker's own thread: `raise` kills the worker (crash drill),
        # `hang` wedges it past the supervisor deadline (hang drill)
        fault_point("sebulba.env_worker")
        if self.deposed:
            raise _Deposed()

    def _push_abort(self) -> bool:
        """Generation fence evaluated by ``TrajQueue.put`` UNDER ITS LOCK
        right before the append (and on every backpressure wait slice,
        where it also refreshes the heartbeat): a deposed worker blocked
        in ``put`` aborts instead of delivering a stale-generation
        segment."""
        self.touch()
        return self.deposed or self.stop_event.is_set()

    def run(self) -> None:
        envs = None
        try:
            envs = self.env_builder()
            obs, _ = envs.reset(seed=self.seed)
            self.protocol.on_reset(self, obs)
            while not self.stop_event.is_set() and not self.deposed:
                version_at_start = self._last_version
                obs, segment, ep_stats, steps = self.protocol.run_segment(
                    self, envs, obs, self.rollout_steps
                )
                self.env_steps += steps
                if self.stats_sink and ep_stats:
                    self.stats_sink(ep_stats)
                if self.deposed or self.stop_event.is_set():
                    break  # partial/stale work dies with the worker
                self.traj_queue.put(
                    segment,
                    meta={
                        "version": version_at_start,
                        "worker": self.worker_id,
                        "env_steps": steps,
                        "generation": self.generation,
                    },
                    abort=self._push_abort,
                )
                self.segments_pushed += 1
        except (_Deposed, ServiceStopped):
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced to the supervisor
            if not self.stop_event.is_set():
                self.error = e
        finally:
            if envs is not None:
                try:
                    envs.close()
                except Exception:
                    pass


class _Deposed(RuntimeError):
    """Raised inside a worker the supervisor gave up on (hang respawn)."""


class WorkerSupervisor:
    """Respawn policy for the env-worker fleet.

    Each worker heartbeats per env step; the supervisor's :meth:`check`
    (driven from the learner loop — no extra polling thread) deposes
    workers that died (uncaught exception) or stalled past
    ``deadline_s`` and respawns them with fresh envs and a bumped
    generation, up to ``max_restarts`` total.  Deposed workers can never
    push (generation fencing in :class:`EnvWorker`), so a respawn cannot
    tear or duplicate trajectories.
    """

    def __init__(
        self,
        spawn: Callable[[int, int], EnvWorker],
        num_workers: int,
        *,
        deadline_s: float = 120.0,
        max_restarts: int = 3,
    ):
        self.spawn = spawn
        self.deadline_s = float(deadline_s)
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.workers: List[EnvWorker] = [spawn(i, 0) for i in range(num_workers)]

    def start(self) -> None:
        for w in self.workers:
            w.start()

    def check(self) -> None:
        """Depose/respawn wedged or dead workers; raise when the restart
        budget is exhausted or a worker failed with a non-respawnable
        error while the budget is empty."""
        now = time.monotonic()
        for i, w in enumerate(self.workers):
            dead = not w.is_alive() and w.error is not None
            hung = w.is_alive() and (now - w.last_beat) > self.deadline_s
            if not (dead or hung):
                continue
            if self.restarts >= self.max_restarts:
                raise RuntimeError(
                    f"env worker {w.worker_id} {'died' if dead else 'hung'} "
                    f"with the restart budget exhausted "
                    f"({self.max_restarts})"
                ) from w.error
            self.restarts += 1
            w.deposed = True  # a hung thread exits at its next beat
            import logging

            logging.getLogger(__name__).warning(
                "sebulba: env worker %d %s (%s); respawning (restart %d/%d)",
                w.worker_id,
                "died" if dead else f"hung for {now - w.last_beat:.1f}s",
                w.error,
                self.restarts,
                self.max_restarts,
            )
            from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

            RESILIENCE_MONITOR.record_stall(f"sebulba.env_worker[{w.worker_id}]")
            fresh = self.spawn(w.worker_id, w.generation + 1)
            self.workers[i] = fresh
            fresh.start()

    def stop(self, join_timeout: float = 10.0) -> None:
        for w in self.workers:
            w.deposed = True
        for w in self.workers:
            w.join(join_timeout)

    def alive(self) -> int:
        return sum(1 for w in self.workers if w.is_alive())


class FusedActor(threading.Thread):
    """Anakin-style on-device rollout shard: one per actor device, for
    pure-JAX envs.  The whole rollout (env scan + policy + bootstrap) is
    one compile-once executable over a donated device-resident carry; each
    finished segment moves device-to-device into the trajectory queue.
    Steady state performs zero H2D transfers — ``guard`` arms
    ``jax.transfer_guard_host_to_device("disallow")`` around post-warmup
    windows to prove it.
    """

    def __init__(
        self,
        index: int,
        device: Any,
        rollout_exe: AOTFunction,
        carry: Any,
        key: jax.Array,
        broadcast: ParamBroadcast,
        traj_queue: TrajQueue,
        *,
        segment_meta: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None,
        stop_event: Optional[threading.Event] = None,
        stats_sink: Optional[Callable[[Sequence[Tuple[float, int]]], None]] = None,
        env_steps_per_segment: int = 0,
        guard: bool = False,
    ):
        super().__init__(name=f"sebulba.fused_actor[{index}]", daemon=True)
        self.index = int(index)
        self.device = device
        self.rollout_exe = rollout_exe
        self._carry = carry
        self._key = jax.device_put(key, device)
        self.broadcast = broadcast
        self.traj_queue = traj_queue
        self.segment_meta = segment_meta
        self.stop_event = stop_event or threading.Event()
        self.stats_sink = stats_sink
        self.env_steps_per_segment = int(env_steps_per_segment)
        self.guard = bool(guard)
        self.error: Optional[BaseException] = None
        self.segments_pushed = 0
        self.env_steps = 0
        self.idle_s = 0.0
        self.busy_s = 0.0

    def actor_idle_frac(self) -> float:
        total = self.idle_s + self.busy_s
        return self.idle_s / total if total > 0 else 0.0

    def cache_sizes(self) -> Dict[int, int]:
        return {0: self.rollout_exe.cache_size()}

    def run(self) -> None:
        from sheeprl_tpu.data.device_replay import steady_guard

        try:
            windows = 0
            while not self.stop_event.is_set():
                t0 = time.perf_counter()
                params, version = self.broadcast.fetch(self.index)
                with steady_guard(self.guard and windows > 0):
                    self._carry, segment, last_obs, stats, self._key = self.rollout_exe(
                        params, self._carry, self._key
                    )
                # the dispatch is async: block here so busy/idle measure the
                # DEVICE's rollout time, not the host enqueue (the
                # actor_idle_frac gauge is the topology-tuning signal)
                jax.block_until_ready(self._key)
                windows += 1
                t1 = time.perf_counter()
                self.busy_s += t1 - t0
                if self.stats_sink is not None:
                    from sheeprl_tpu.envs.jax.anakin import episode_stats_from_device

                    rets, lens = episode_stats_from_device(stats)
                    if rets.size:
                        self.stats_sink(list(zip(rets.tolist(), lens.tolist())))
                segment = dict(segment)
                segment.update({f"last_{k}": v for k, v in last_obs.items()})
                meta = {
                    "version": version,
                    "worker": self.index,
                    "env_steps": self.env_steps_per_segment,
                    "generation": 0,
                }
                if self.stop_event.is_set():
                    break
                self.traj_queue.put(segment, meta=meta)
                self.segments_pushed += 1
                self.env_steps += self.env_steps_per_segment
                self.idle_s += time.perf_counter() - t1
        except ServiceStopped:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced by the runner
            if not self.stop_event.is_set():
                self.error = e
