"""Sebulba PPO: the decoupled PPO loop rebuilt on the actor–learner device
split (``topology=sebulba``; docs/sebulba.md).

Dataflow, per :mod:`sheeprl_tpu.parallel.topology`:

* **cpu-gym actors** — ``topology.env_workers`` driver threads step env
  slices (subprocess workers under ``env.sync_env=False``) and round-trip
  observation blocks through the actor devices' batched AOT inference
  engines; each worker assembles ``(T, B_w)`` segments and pushes them
  into the device-resident trajectory queue.
* **jax-env actors** (``env=jax_*``) — each actor device runs an
  Anakin-style fused rollout shard (env scan + policy + truncation
  bootstrap in ONE executable over a donated carry); segments move
  device-to-device into the queue.
* **learner** — pops one segment per producer, and its compiled
  ``learner_phase`` concatenates them along the env axis, recomputes
  values, runs GAE + all epochs/minibatches (the exact
  ``ppo_decoupled`` train program), then broadcasts fresh params
  learner→actors with the :class:`~sheeprl_tpu.parallel.topology.
  ParamBroadcast` staleness gate.

The learner runs on the calling thread; actors and workers are threads
(JAX dispatch is thread-safe, and XLA execution releases the GIL, so
actor inference genuinely overlaps learner optimization even before the
device split makes them independent).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent, sample_actions
from sheeprl_tpu.algos.ppo.ppo_decoupled import _build_train_fns
from sheeprl_tpu.algos.ppo.utils import (
    actions_for_env,
    normalize_obs_keys,
    obs_to_np,
    spaces_to_dims,
    test,
)
from sheeprl_tpu.parallel.topology import DeviceTopology, ParamBroadcast, topology_cfg
from sheeprl_tpu.sebulba.actor import ActorEngine, EnvWorker, FusedActor, WorkerSupervisor, derive_ladder
from sheeprl_tpu.sebulba.queues import ObsQueue, TrajQueue
from sheeprl_tpu.sebulba.runner import (
    StatsSink,
    arm_preemption,
    build_worker_fleet,
    clamp_queue_slots,
    collect_run_stats,
    drain_preemptible,
    shutdown,
)
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


class PPOWorkerProtocol:
    """Per-step semantics of a PPO env worker: prepared-observation blocks
    out, sampled actions back, truncation bootstrap via a SECOND inference
    request on the (padded) final-obs block — same shape, same executable,
    no ladder churn."""

    def __init__(self, obs_keys, cnn_keys, mlp_keys, act_space, gamma):
        self.obs_keys = tuple(obs_keys)
        self.cnn_keys = tuple(cnn_keys)
        self.mlp_keys = tuple(mlp_keys)
        self.act_space = act_space
        self.gamma = float(gamma)

    def prepare(self, obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for k in self.cnn_keys:
            out[k] = obs_to_np(obs[k], is_image=True)
        for k in self.mlp_keys:
            out[k] = obs_to_np(obs[k], is_image=False)
        return out

    def on_reset(self, worker: EnvWorker, obs: Dict[str, np.ndarray]) -> None:
        pass

    def run_segment(
        self, worker: EnvWorker, envs: Any, obs: Dict[str, np.ndarray], steps: int
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], List[Tuple[float, int]], int]:
        num_envs = envs.num_envs
        rows: Dict[str, List[np.ndarray]] = {k: [] for k in self.obs_keys}
        for k in ("actions", "logprobs", "rewards", "dones"):
            rows[k] = []
        ep_stats: List[Tuple[float, int]] = []
        for _ in range(steps):
            worker.beat()
            block = self.prepare(obs)
            out = worker.infer(block)
            actions = np.asarray(out["actions"])
            next_obs, rewards, terminated, truncated, info = envs.step(
                actions_for_env(actions, self.act_space)
            )
            rewards = np.asarray(rewards, np.float32)
            dones = np.logical_or(terminated, truncated)
            if np.any(truncated):
                # truncation bootstrap r += γ·V(final_obs): the final-obs
                # batch is padded to the full block so the actor serves it
                # from the SAME ladder rung (reference: ppo.py:287-306)
                final_obs = final_obs_rows(info, np.nonzero(truncated)[0], self.obs_keys)
                if final_obs is not None:
                    padded = {k: np.asarray(next_obs[k]).copy() for k in self.obs_keys}
                    for k in self.obs_keys:
                        padded[k][truncated] = final_obs[k]
                    vout = worker.infer(self.prepare(padded))
                    vals = np.asarray(vout["values"])
                    rewards[truncated] += self.gamma * vals[truncated]
            for k in self.obs_keys:
                rows[k].append(block[k])
            rows["actions"].append(actions.reshape(num_envs, -1))
            rows["logprobs"].append(np.asarray(out["logprobs"]).reshape(num_envs))
            rows["rewards"].append(rewards.reshape(num_envs))
            rows["dones"].append(dones.astype(np.float32).reshape(num_envs))
            obs = next_obs
            ep_stats.extend(episode_stats(info))
        segment = {k: np.stack(v, axis=0) for k, v in rows.items()}
        last = self.prepare(obs)
        for k in self.obs_keys:
            segment[f"last_{k}"] = last[k]
        return obs, segment, ep_stats, steps * num_envs


def run_sebulba(fabric: Any, cfg: Any) -> Dict[str, Any]:
    """Train decoupled PPO through the Sebulba topology.  Returns a stats
    dict (throughput/queue/staleness counters) for ``bench.py``."""
    if fabric.num_processes > 1:
        # multi-process runs split actors and learner across HOSTS, not
        # devices: the in-process topology below assumes one device view
        from sheeprl_tpu.sebulba.pod import run_pod

        return run_pod(fabric, cfg)
    from sheeprl_tpu.envs.jax.registry import is_jax_native

    topo_cfg = topology_cfg(cfg)
    topo = DeviceTopology.from_config(fabric, cfg)
    learner_fab = topo.learner_fabric
    fabric.print(topo.describe())
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    save_configs(cfg, log_dir)

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    jax_native = is_jax_native(cfg)
    num_actors = topo.num_actors

    # ---------------- spaces -------------------------------------------------
    if jax_native:
        from sheeprl_tpu.envs.jax.core import VectorJaxEnv
        from sheeprl_tpu.envs.jax.registry import jax_env_from_cfg

        if num_envs % num_actors:
            raise ValueError(
                f"sebulba jax actors need env.num_envs ({num_envs}) divisible "
                f"by topology.actor_devices ({num_actors})"
            )
        envs_per_actor = num_envs // num_actors
        venvs = [VectorJaxEnv(jax_env_from_cfg(cfg), envs_per_actor) for _ in range(num_actors)]
        obs_space = venvs[0].single_observation_space
        act_space = venvs[0].single_action_space
        num_workers = num_actors
    else:
        num_workers = max(1, int(topo_cfg.get("env_workers", 2)))
        if num_envs % num_workers:
            raise ValueError(
                f"sebulba env workers need env.num_envs ({num_envs}) divisible "
                f"by topology.env_workers ({num_workers})"
            )
        probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
        obs_space, act_space = probe.observation_space, probe.action_space
        probe.close()
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")
    gamma = float(cfg.algo.gamma)

    # ---------------- learner: agent + train program -------------------------
    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        key = jnp.asarray(state["key"])
    agent, params = build_agent(learner_fab, actions_dim, is_continuous, cfg, obs_space, state.get("agent"))
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    opt_state = learner_fab.replicate(state.get("opt_state") or optimizer.init(params))

    _, _, _, train_phase_raw = _build_train_fns(
        agent, optimizer, cfg, obs_keys, actions_dim, is_continuous, dist_type
    )

    T, B = rollout_steps, num_envs
    global_bs = min(int(cfg.algo.per_rank_batch_size) * learner_fab.world_size, T * B)
    num_minibatches = -(-T * B // global_bs)
    n_producers = num_workers

    def learner_phase(p, o_state, segs, k, clip_coef, ent_coef):
        """Concat the producers' segments along the env axis + the full
        decoupled PPO train program, in ONE learner-mesh executable."""
        rollout = {
            kk: jnp.concatenate([s[kk] for s in segs], axis=1)
            for kk in obs_keys + ("actions", "logprobs", "rewards", "dones")
        }
        last_obs = {
            kk: jnp.concatenate([s[f"last_{kk}"] for s in segs], axis=0) for kk in obs_keys
        }
        return train_phase_raw(
            p, o_state, rollout, last_obs, k, clip_coef, ent_coef,
            batch_size=global_bs, num_minibatches=num_minibatches,
        )

    # donate params/opt only: the concat re-lays the segment buffers out, so
    # XLA cannot reuse them anyway (donating them just prints the
    # "donated buffers were not usable" warning)
    learner_phase = learner_fab.compile(
        learner_phase,
        name=f"{cfg.algo.name}.sebulba_learner_phase",
        donate_argnums=(0, 1),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    # ---------------- broadcast + queues -------------------------------------
    broadcast = ParamBroadcast(
        fabric,
        topo.actor_devices,
        max_staleness=int(topo_cfg.get("max_staleness", 2)),
        gate_timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    sync_every = max(1, int(topo_cfg.get("sync_every", 1)))

    traj_queue = TrajQueue(
        clamp_queue_slots(topo_cfg, n_producers),
        rollout_steps,
        learner_fab,
        stage=True,
        bootstrap_keys=tuple(f"last_{k}" for k in obs_keys),
        timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    stats_sink = StatsSink()
    stop_event = threading.Event()
    guard_on = bool(cfg.buffer.get("transfer_guard", False))

    # ---------------- actors -------------------------------------------------
    engines: List[Any] = []
    supervisor: Optional[WorkerSupervisor] = None
    obs_queue: Optional[ObsQueue] = None

    if jax_native:
        from sheeprl_tpu.envs.jax.anakin import make_rollout_fn
        from sheeprl_tpu.parallel.compile import compile_once

        def _sample(out, k):
            return sample_actions(out, actions_dim, is_continuous, k, dist_type=dist_type)

        for i, (dev, venv) in enumerate(zip(topo.actor_devices, venvs)):
            rollout_fn = make_rollout_fn(
                venv, agent.apply, _sample,
                cnn_keys=cnn_keys, mlp_keys=mlp_keys, action_space=act_space,
                gamma=gamma, rollout_steps=rollout_steps,
            )

            def actor_rollout(p, actor, k, _roll=rollout_fn):
                k_roll, k_next = jax.random.split(k)
                actor, traj, last_obs, stats = _roll(p, actor, k_roll)
                return actor, traj, last_obs, stats, k_next

            exe = compile_once(
                actor_rollout,
                name=f"sebulba.fused_rollout[{i}]",
                donate_argnums=(1, 2),
                max_recompiles=cfg.algo.get("max_recompiles"),
            )
            env_state, _ = venv.reset(jax.random.fold_in(key, 0xAC + i))
            carry = jax.device_put(
                {
                    "env": env_state,
                    "ep_ret": jnp.zeros((venv.num_envs,), jnp.float32),
                    "ep_len": jnp.zeros((venv.num_envs,), jnp.int32),
                    "update": jnp.asarray(0, jnp.int32),
                },
                dev,
            )
            engines.append(
                FusedActor(
                    i, dev, exe, carry, jax.random.fold_in(key, 0xF0 + i), broadcast,
                    traj_queue,
                    stop_event=stop_event,
                    stats_sink=stats_sink,
                    env_steps_per_segment=rollout_steps * venv.num_envs,
                    guard=guard_on,
                )
            )
    else:
        envs_per_worker = num_envs // num_workers
        protocol = PPOWorkerProtocol(obs_keys, cnn_keys, mlp_keys, act_space, gamma)
        obs_queue = ObsQueue(max_pending=2 * num_workers)
        ladder = derive_ladder(
            envs_per_worker, num_workers, topo_cfg.get("actor_batch_ladder")
        )

        def policy_fn(p, obs, k):
            k_sample, k_next = jax.random.split(k)
            out, value = agent.apply(p, obs)
            actions, logprob, _ = sample_actions(
                out, actions_dim, is_continuous, k_sample, dist_type=dist_type
            )
            return {"actions": actions, "logprobs": logprob, "values": value[..., 0]}, k_next

        # prepared-obs leaf spec (post obs_to_np layout) from a probe reset
        probe_prep = protocol.prepare(
            {k: np.zeros((1,) + tuple(obs_space[k].shape), obs_space[k].dtype) for k in obs_keys}
        )
        obs_spec = {k: (tuple(v.shape[1:]), v.dtype) for k, v in probe_prep.items()}
        param_spec = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
        for i, dev in enumerate(topo.actor_devices):
            eng = ActorEngine(
                i, dev, policy_fn, obs_spec, param_spec, ladder, envs_per_worker,
                obs_queue, broadcast, jax.random.fold_in(key, 0xF0 + i),
                max_wait_s=float(topo_cfg.get("max_wait_ms", 20.0)) / 1e3,
                max_recompiles=cfg.algo.get("max_recompiles"),
            )
            if cfg.algo.get("compile_warmup", True):
                eng.warmup(fabric.compile_pool, join=False)
            engines.append(eng)
        fabric.compile_pool.join()

        supervisor = build_worker_fleet(
            cfg, topo_cfg,
            protocol=protocol, obs_queue=obs_queue, traj_queue=traj_queue,
            segment_steps=rollout_steps, num_workers=num_workers,
            envs_per_worker=envs_per_worker, log_dir=log_dir,
            stop_event=stop_event, stats_sink=stats_sink,
        )

    # ---------------- counters -----------------------------------------------
    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)
    policy_steps_per_iter = num_envs * rollout_steps
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    clip_coef_v = float(cfg.algo.clip_coef)
    ent_coef_v = float(cfg.algo.ent_coef)
    base_lr = float(cfg.algo.optimizer.lr)

    staleness_sum = 0
    staleness_max = 0
    segments_consumed = 0
    env_steps_consumed = 0
    updates_done = 0
    last_losses = None
    t_start = time.perf_counter()

    # ---------------- run ----------------------------------------------------
    # queue/broadcast counters become live hub sources for the duration of
    # the run (scrapeable via /metrics mid-run, not just at log intervals);
    # a fresh span window makes the end-of-run phase breakdown cover the
    # training loop, not agent construction/compilation
    from sheeprl_tpu.telemetry import HUB, SPANS

    HUB.register("sebulba.traj_queue", traj_queue.metrics)
    HUB.register("sebulba.broadcast", broadcast.metrics)
    SPANS.roll_window()

    arm_preemption(cfg)

    def save_checkpoint() -> None:
        # closure over the live loop variables: the cadence save and the
        # preemption final save must write the identical state
        fabric.call(
            "on_checkpoint_player",
            ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt"),
            state={
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            },
        )

    try:
        # inside the try: the first publish crosses fabric.copy_to (a
        # fault-injection site) — a throw here must still unregister
        broadcast.publish(params, version=start_iter - 1)
        for eng in engines:
            eng.start()
        if supervisor is not None:
            supervisor.start()
        update = start_iter - 1
        for update in range(start_iter, total_iters + 1):
            with timer("Time/env_interaction_time"):
                items = drain_preemptible(
                    traj_queue, n_producers, engines, supervisor,
                    ckpt_mgr=ckpt_mgr, fabric=fabric, policy_step=policy_step,
                    save_checkpoint=save_checkpoint,
                )
            if items is None:  # preempted mid-wait: committed save done
                break
            segs = tuple(item[0] for item in items)
            for _, meta in items:
                lag = broadcast.version - int(meta.get("version", 0))
                staleness_sum += lag
                staleness_max = max(staleness_max, lag)
                env_steps_consumed += int(meta.get("env_steps", 0))
            segments_consumed += len(items)
            policy_step += policy_steps_per_iter
            updates_done += 1

            with timer("Time/train_time"):
                key, tk = jax.random.split(key)
                params, opt_state, last_losses = learner_phase(
                    params, opt_state, segs, tk,
                    jnp.float32(clip_coef_v), jnp.float32(ent_coef_v),
                )
            if update % sync_every == 0 or update == total_iters:
                broadcast.publish(params, version=update)
                broadcast.gate()
            if supervisor is not None:
                supervisor.check()

            # schedules (host-side, like the pipelined decoupled loop)
            if cfg.algo.anneal_lr:
                opt_state = set_learning_rate(
                    opt_state,
                    polynomial_decay(update, initial=base_lr, final=0.0, max_decay_steps=total_iters),
                )
            if cfg.algo.anneal_clip_coef:
                clip_coef_v = polynomial_decay(
                    update, initial=float(cfg.algo.clip_coef), final=0.0, max_decay_steps=total_iters
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef_v = polynomial_decay(
                    update, initial=float(cfg.algo.ent_coef), final=0.0, max_decay_steps=total_iters
                )

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
            ):
                for ep_ret, ep_len in stats_sink.drain():
                    aggregator.update("Rewards/rew_avg", float(ep_ret))
                    aggregator.update("Game/ep_len_avg", int(ep_len))
                if last_losses is not None:
                    pg, vl, ent = last_losses
                    aggregator.update("Loss/policy_loss", pg)
                    aggregator.update("Loss/value_loss", vl)
                    aggregator.update("Loss/entropy_loss", ent)
                extra = dict(traj_queue.metrics())
                extra.update(broadcast.metrics())
                extra["Sebulba/traj_staleness_max"] = float(staleness_max)
                extra["Sebulba/traj_staleness_avg"] = (
                    staleness_sum / max(segments_consumed, 1)
                )
                extra["Sebulba/actor_idle_frac"] = float(
                    np.mean([eng.actor_idle_frac() for eng in engines])
                )
                last_log = flush_metrics(
                    aggregator, timer, logger, policy_step, last_log, extra_metrics=extra
                )

            if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
                last_checkpoint = policy_step
                save_checkpoint()
            if ckpt_mgr.preempted:
                fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
                break
    finally:
        # unregister on EVERY exit (timeout/staleness/engine errors
        # included): a leaked source would pin the dead run's queue ring
        # and report its stale gauges into the next run's flushes
        HUB.unregister("sebulba.traj_queue")
        HUB.unregister("sebulba.broadcast")
        shutdown(stop_event, traj_queue, obs_queue, engines, supervisor)

    run_stats = collect_run_stats(
        topo=topo, updates=updates_done,
        wall_s=time.perf_counter() - t_start, env_steps=env_steps_consumed,
        engines=engines, traj_queue=traj_queue, broadcast=broadcast,
        traj_staleness_max=staleness_max, traj_staleness_sum=staleness_sum,
        segments_consumed=segments_consumed, supervisor=supervisor,
    )

    ckpt_mgr.finalize()
    if cfg.algo.run_test and not ckpt_mgr.preempted:
        test(agent, fabric.to_host(params), cfg, log_dir, logger)
    if logger is not None:
        logger.close()
    return run_stats
