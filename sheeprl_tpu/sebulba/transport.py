"""The pod's DCN transport: cross-host trajectory intake + param serving.

Two endpoints of one contract (docs/distributed.md):

* :class:`LearnerFront` — the HTTP server the learner cell owns.  It is
  the cross-host face of the two Sebulba primitives: trajectory segments
  POSTed by remote actor cells flow — CRC-verified — into the learner's
  ordinary :class:`~sheeprl_tpu.sebulba.queues.TrajQueue` (same
  never-drop/torn-segment-reject contract as the in-process path), and
  fresh params are GET-served with the same versioned ``max_staleness``
  gate :class:`~sheeprl_tpu.parallel.topology.ParamBroadcast` enforces
  in-process (:class:`DcnParamBroadcast` below literally *is* a
  ParamBroadcast whose publish side serializes instead of device-copies).
  A ``/poll`` control plane rides along: commit-step announcements,
  coordinated preemption, per-cell telemetry snapshots (rank-0
  aggregation), and liveness (an actor cell silent past
  ``heartbeat_grace_s`` raises :class:`~sheeprl_tpu.parallel.distributed.
  PeerLost` into the learner loop).

* :class:`PodClient` — the actor cell's side.  ``push_segment`` retries
  backpressure (503) and torn rejects (409) until ``push_deadline_s``
  — never drops; ``fetch_params`` verifies the CRC before unpickling (a
  damaged broadcast is refetched, never applied); ``poll`` reports the
  applied param version, the local preemption latch and a telemetry
  snapshot, and returns the learner's control word.

Fault sites: ``dcn.traj`` (the segment payload on the wire, per push
attempt) and ``dcn.broadcast`` (the param payload, per fetch) — both
byte sites stamped AFTER the CRC, so injected corruption/truncation is
exactly what the receiving side's CRC check must catch.
"""

from __future__ import annotations

import json
import pickle
import socket
import threading
import time
import urllib.error
import urllib.request
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from sheeprl_tpu.parallel.distributed import PeerLost, is_fake_dcn
from sheeprl_tpu.parallel.topology import ParamBroadcast
from sheeprl_tpu.resilience.faults import fault_bytes
from sheeprl_tpu.sebulba.queues import TornTrajectory, TrajQueue
from sheeprl_tpu.serve.batcher import QueueFull, ServiceStopped

_KV_FRONT_KEY = "sheeprl_tpu/dcn/front"


class SegmentPushError(RuntimeError):
    """A segment could not be delivered within ``push_deadline_s`` — the
    never-drop contract fails LOUDLY, it does not discard."""


def _crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def advertise_host() -> str:
    """The address remote cells can reach this host at (loopback for the
    fake-DCN pod, the hostname's address for real multi-host pods)."""
    if is_fake_dcn():
        return "127.0.0.1"
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return socket.getfqdn()


def publish_front_address(address: str) -> None:
    """Advertise the learner front's address through the jax.distributed
    KV store so actor cells need no address config at all."""
    from sheeprl_tpu.parallel.distributed import _kv_client

    _kv_client().key_value_set_bytes(_KV_FRONT_KEY, address.encode())


def lookup_front_address(timeout_s: float = 120.0) -> str:
    from sheeprl_tpu.parallel.distributed import _kv_client

    raw = _kv_client().blocking_key_value_get_bytes(_KV_FRONT_KEY, int(timeout_s * 1000))
    return raw.decode()


class DcnParamBroadcast(ParamBroadcast):
    """ParamBroadcast's cross-DCN flavor: same versioned ``max_staleness``
    gate, serialized transport.

    ``publish`` pickles the (actor subtree of the) host params ONCE and
    stamps the CRC; remote fetches are served from that buffer.  The fetch
    cursors that feed the inherited :meth:`~ParamBroadcast.gate` advance
    on :meth:`note_applied` — when an actor cell's ``/poll`` reports the
    version it has actually installed — not at serve time, so a fetch
    lost on the wire (or rejected by the client's CRC check) cannot
    satisfy the staleness gate.
    """

    def __init__(
        self,
        actor_ranks: List[int],
        extract: Callable[[Any], Any] = lambda p: p,
        max_staleness: int = 2,
        gate_timeout_s: float = 300.0,
    ):
        # the parent's fabric/device plumbing is unused: publish/fetch are
        # overridden to move bytes, and the gate logic is device-free
        super().__init__(
            fabric=None,
            actor_devices=list(actor_ranks),
            extract=extract,
            max_staleness=max_staleness,
            gate_timeout_s=gate_timeout_s,
        )
        self.actor_ranks = list(actor_ranks)
        self._payload: Optional[bytes] = None
        self._payload_crc = 0
        self.bytes_published = 0

    def publish(self, params: Any, version: Optional[int] = None) -> int:
        from sheeprl_tpu.telemetry.spans import span

        with span("param.broadcast"):
            payload = pickle.dumps(self.extract(params), protocol=pickle.HIGHEST_PROTOCOL)
            crc = _crc(payload)
        with self._lock:
            first = self.publishes == 0
            self._version = int(version) if version is not None else self._version + 1
            if first:
                self._fetched_version = [self._version] * len(self.actor_ranks)
            self._payload = payload
            self._payload_crc = crc
            self.publishes += 1
            self.bytes_published += len(payload)
            self._fetched.notify_all()
            return self._version

    def payload_for(self, have_version: int) -> Optional[Tuple[bytes, int, int]]:
        """``(payload, crc, version)`` when newer than ``have_version``
        (else None).  Serving does NOT advance the gate cursors."""
        with self._lock:
            if self._payload is None or self._version <= int(have_version):
                return None
            return self._payload, self._payload_crc, self._version

    def note_applied(self, rank: int, version: int) -> None:
        """An actor cell reported (via ``/poll``) the version it runs."""
        try:
            idx = self.actor_ranks.index(int(rank))
        except ValueError:
            return
        with self._lock:
            lag = self._version - int(version)
            if int(version) > self._fetched_version[idx]:
                self._fetched_version[idx] = int(version)
            self.fetches += 1
            self.staleness_sum += max(lag, 0)
            self.staleness_max = max(self.staleness_max, lag)
            self._fetched.notify_all()

    def fetch(self, actor_index: int) -> tuple:  # pragma: no cover - guard
        raise NotImplementedError(
            "DcnParamBroadcast is fetched over HTTP (PodClient.fetch_params)"
        )

    def metrics(self) -> Dict[str, float]:
        out = super().metrics()
        with self._lock:
            out["Dcn/broadcast_bytes"] = float(self.bytes_published)
            out["Dcn/broadcast_publishes"] = float(self.publishes)
        return out


class LearnerFront:
    """The learner cell's DCN server: segment intake, param serving, and
    the pod control plane, on one ``ThreadingHTTPServer``.

    Exposes ``.error`` exactly like an actor engine so the learner's
    ordinary :func:`~sheeprl_tpu.sebulba.runner.drain_segments` loop
    surfaces transport/liveness failures: a peer silent past
    ``heartbeat_grace_s`` (after first contact; ``first_contact_grace_s``
    covers the remote cells' compile time) sets ``.error`` to
    :class:`PeerLost` and the next drain slice raises it.
    """

    def __init__(
        self,
        traj_queue: TrajQueue,
        broadcast: DcnParamBroadcast,
        expected_actors: List[int],
        *,
        host: Optional[str] = None,
        port: int = 0,
        heartbeat_grace_s: float = 30.0,
        first_contact_grace_s: float = 300.0,
        put_timeout_s: float = 5.0,
    ):
        self.traj_queue = traj_queue
        self.broadcast = broadcast
        self.expected_actors = list(expected_actors)
        self.heartbeat_grace_s = float(heartbeat_grace_s)
        self.first_contact_grace_s = float(first_contact_grace_s)
        self.put_timeout_s = float(put_timeout_s)
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self._last_seen: Dict[int, float] = {}
        self._goodbyes: Dict[int, str] = {}
        self._latched: set = set()
        self._peer_metrics: Dict[int, Dict[str, float]] = {}
        self._commit_step = -1
        # recent announcements, oldest first: a fast learner can announce
        # two saves between actor polls (the commit manager runs async),
        # and a latest-wins slot would silently coalesce the earlier step
        # — its shard would never be written and rank 0's commit would
        # time out.  Actors replay every step on this list.
        self._commit_steps: List[int] = []
        self._preempt = False
        self._done = False
        self._stopped = False
        self.error: Optional[BaseException] = None
        # Dcn/* counters
        self.segments_accepted = 0
        self.segments_rejected = 0
        self.segment_bytes = 0
        self.backpressured = 0

        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args: Any) -> None:  # quiet
                pass

            def _reply(self, code: int, body: bytes = b"", headers: Optional[Dict[str, str]] = None) -> None:
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _reply_json(self, code: int, obj: Dict[str, Any]) -> None:
                body = json.dumps(obj).encode()
                self._reply(code, body, {"Content-Type": "application/json"})

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0) or 0)
                return self.rfile.read(n) if n else b""

            def do_GET(self) -> None:
                try:
                    if self.path.startswith("/healthz"):
                        self._reply_json(200, {"ok": True, "actors": len(front._last_seen)})
                    elif self.path.startswith("/params"):
                        front._serve_params(self)
                    else:
                        self._reply(404)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self) -> None:
                try:
                    if self.path.startswith("/segment"):
                        front._accept_segment(self)
                    elif self.path.startswith("/poll"):
                        front._accept_poll(self)
                    elif self.path.startswith("/goodbye"):
                        front._accept_goodbye(self)
                    else:
                        self._reply(404)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        self._httpd = ThreadingHTTPServer((host or advertise_host(), int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[0], self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="dcn.front", daemon=True
        )
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="dcn.front.monitor", daemon=True
        )

    # -- lifecycle ------------------------------------------------------------
    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "LearnerFront":
        self._serve_thread.start()
        self._monitor_thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass

    def join(self, timeout: Optional[float] = None) -> None:
        self._serve_thread.join(timeout)

    # -- handler bodies (run on server threads) -------------------------------
    def _serve_params(self, handler: Any) -> None:
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(handler.path).query)
        have = int(q.get("have", ["-1"])[0])
        served = self.broadcast.payload_for(have)
        if served is None:
            handler._reply(204)
            return
        payload, crc, version = served
        # the dcn.broadcast fault site: wire damage AFTER the CRC stamp,
        # per fetch — the client's CRC check rejects and refetches
        payload = fault_bytes("dcn.broadcast", payload)
        handler._reply(
            200,
            payload,
            {
                "Content-Type": "application/octet-stream",
                "X-Sheeprl-Version": str(version),
                "X-Sheeprl-CRC32": str(crc),
            },
        )

    def _accept_segment(self, handler: Any) -> None:
        payload = handler._read_body()
        want_crc = int(handler.headers.get("X-Sheeprl-CRC32", "-1"))
        if _crc(payload) != want_crc:
            # torn segment: the wire damaged it (or the dcn.traj fault
            # site did) — REJECT, never enqueue; the sender retries
            with self._lock:
                self.segments_rejected += 1
            from sheeprl_tpu.telemetry import RECORDER

            RECORDER.record("dcn.torn_segment", rank=handler.headers.get("X-Sheeprl-Rank"))
            handler._reply_json(409, {"error": "crc mismatch: torn segment rejected"})
            return
        meta = json.loads(handler.headers.get("X-Sheeprl-Meta", "{}") or "{}")
        rank = int(handler.headers.get("X-Sheeprl-Rank", -1))
        self._touch(rank)
        try:
            segment = pickle.loads(payload)
        except Exception:
            with self._lock:
                self.segments_rejected += 1
            handler._reply_json(409, {"error": "undecodable segment rejected"})
            return
        deadline = time.monotonic() + self.put_timeout_s
        try:
            # bounded put: the HTTP reply IS the backpressure signal (the
            # client retries 503), so never sit on a server thread for the
            # queue's full multi-minute timeout
            self.traj_queue.put(
                segment,
                meta=meta,
                abort=lambda: self._stopped or time.monotonic() > deadline,
            )
        except TornTrajectory as e:
            # the queue's own validation (wrong segment length) holds
            # across the process boundary: same reject, different wire code
            with self._lock:
                self.segments_rejected += 1
            handler._reply_json(409, {"error": f"torn segment rejected: {e}"})
            return
        except ServiceStopped:
            if self._stopped or self._done:
                handler._reply_json(410, {"error": "learner gone"})
            else:
                with self._lock:
                    self.backpressured += 1
                handler._reply_json(503, {"error": "trajectory queue full"})
            return
        except QueueFull:
            with self._lock:
                self.backpressured += 1
            handler._reply_json(503, {"error": "trajectory queue full"})
            return
        with self._lock:
            self.segments_accepted += 1
            self.segment_bytes += len(payload)
        handler._reply_json(200, {"ok": True})

    def _accept_poll(self, handler: Any) -> None:
        body = json.loads(handler._read_body() or b"{}")
        rank = int(body.get("rank", -1))
        self._touch(rank)
        if body.get("applied_version") is not None:
            self.broadcast.note_applied(rank, int(body["applied_version"]))
        if body.get("latched"):
            with self._lock:
                self._latched.add(rank)
        hub = body.get("hub")
        if isinstance(hub, dict):
            with self._lock:
                self._peer_metrics[rank] = {
                    str(k): float(v) for k, v in hub.items() if isinstance(v, (int, float))
                }
        with self._lock:
            resp = {
                "version": self.broadcast.version,
                "commit_step": self._commit_step,
                "commit_steps": list(self._commit_steps),
                "preempt": self._preempt or bool(self._latched),
                "done": self._done,
            }
        handler._reply_json(200, resp)

    def _accept_goodbye(self, handler: Any) -> None:
        body = json.loads(handler._read_body() or b"{}")
        rank = int(body.get("rank", -1))
        with self._lock:
            self._goodbyes[rank] = str(body.get("reason", ""))
        handler._reply_json(200, {"ok": True})

    # -- control plane (learner loop side) ------------------------------------
    def _touch(self, rank: int) -> None:
        if rank < 0:
            return
        with self._lock:
            self._last_seen[rank] = time.monotonic()

    def set_commit(self, step: int) -> None:
        """Announce a commit step: every actor cell writes its shard into
        ``step_dir(step)`` when its next poll observes it.  Announcements
        accumulate (bounded) rather than overwrite, so back-to-back saves
        both reach actors that poll less often than the learner commits."""
        with self._lock:
            self._commit_step = int(step)
            self._commit_steps.append(int(step))
            # shards for announcements older than ~16 saves are moot —
            # rank 0's commit wait for them has long expired
            del self._commit_steps[:-16]

    def request_preempt(self) -> None:
        with self._lock:
            self._preempt = True

    def set_done(self) -> None:
        with self._lock:
            self._done = True

    @property
    def actor_latched(self) -> bool:
        """An actor cell's SIGTERM latch, surfaced by its poll — the
        learner adopts it (coordinated preemption crosses the DCN both
        ways)."""
        with self._lock:
            return bool(self._latched)

    def wait_for_cells(self, timeout_s: float = 300.0) -> None:
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if all(r in self._last_seen for r in self.expected_actors):
                    return
            if self.error is not None:
                raise self.error
            time.sleep(0.1)
        with self._lock:
            missing = [r for r in self.expected_actors if r not in self._last_seen]
        raise TimeoutError(f"pod actor cells {missing} never contacted the learner front")

    def wait_goodbyes(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if all(r in self._goodbyes for r in self.expected_actors):
                    return True
            time.sleep(0.1)
        return False

    # -- liveness -------------------------------------------------------------
    def _monitor(self) -> None:
        while not self._stopped:
            time.sleep(1.0)
            if self._stopped or self._done:
                return
            now = time.monotonic()
            with self._lock:
                for rank in self.expected_actors:
                    if rank in self._goodbyes:
                        continue
                    seen = self._last_seen.get(rank)
                    grace = self.heartbeat_grace_s if seen else self.first_contact_grace_s
                    ref = seen if seen else self._started
                    if now - ref > grace:
                        if self.error is None:
                            from sheeprl_tpu.telemetry import RECORDER

                            RECORDER.record(
                                "dcn.peer_lost", rank=rank, silent_s=round(now - ref, 1)
                            )
                            self.error = PeerLost(
                                f"pod actor cell {rank} silent for {now - ref:.1f}s "
                                f"(heartbeat_grace_s={grace:g})"
                            )
                        return

    # -- telemetry ------------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        with self._lock:
            out = {
                "Dcn/segments_accepted": float(self.segments_accepted),
                "Dcn/segments_rejected": float(self.segments_rejected),
                "Dcn/segment_bytes": float(self.segment_bytes),
                "Dcn/backpressured": float(self.backpressured),
                "Dcn/actor_cells": float(len(self._last_seen)),
            }
            # rank-0 aggregation: every cell's hub snapshot, namespaced by
            # pod rank, lands in the learner's metric stream (cells that
            # already namespace their hub keep their own prefix)
            for rank, snap in self._peer_metrics.items():
                for k, v in snap.items():
                    out[k if k.startswith("rank") else f"rank{rank}/{k}"] = v
        out.update(self.broadcast.metrics())
        return out


class PodClient:
    """An actor cell's connection to the learner front."""

    def __init__(
        self,
        address: str,
        rank: int,
        *,
        push_deadline_s: float = 300.0,
        request_timeout_s: float = 10.0,
        heartbeat_grace_s: float = 30.0,
    ):
        self.base = f"http://{address}"
        self.rank = int(rank)
        self.push_deadline_s = float(push_deadline_s)
        self.request_timeout_s = float(request_timeout_s)
        self.heartbeat_grace_s = float(heartbeat_grace_s)
        self._lock = threading.Lock()
        self._first_failure: Optional[float] = None
        # Dcn/* counters
        self.segments_pushed = 0
        self.push_retries = 0
        self.push_wait_s = 0.0
        self.torn_rejected = 0
        self.fetches = 0
        self.fetch_crc_rejects = 0

    # -- plumbing -------------------------------------------------------------
    def _request(
        self, path: str, data: Optional[bytes] = None, headers: Optional[Dict[str, str]] = None
    ) -> Tuple[int, bytes, Dict[str, str]]:
        req = urllib.request.Request(
            self.base + path, data=data, headers=headers or {}, method="POST" if data is not None else "GET"
        )
        try:
            with urllib.request.urlopen(req, timeout=self.request_timeout_s) as resp:
                self._note_ok()
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            self._note_ok()  # the server answered: it is alive
            return e.code, e.read(), dict(e.headers)

    def _note_ok(self) -> None:
        with self._lock:
            self._first_failure = None

    def _note_failure(self) -> None:
        """Track learner silence; raise PeerLost past the grace window —
        the actor cell must not spin against a dead learner forever."""
        now = time.monotonic()
        with self._lock:
            if self._first_failure is None:
                self._first_failure = now
            silent = now - self._first_failure
        if silent > self.heartbeat_grace_s:
            from sheeprl_tpu.telemetry import RECORDER

            RECORDER.record("dcn.peer_lost", rank=0, silent_s=round(silent, 1))
            raise PeerLost(
                f"learner front unreachable for {silent:.1f}s "
                f"(heartbeat_grace_s={self.heartbeat_grace_s:g})"
            )

    # -- data plane -----------------------------------------------------------
    def push_segment(self, segment: Dict[str, Any], meta: Optional[Dict[str, Any]] = None) -> None:
        """Deliver one segment, never dropping: 503 (backpressure) and 409
        (torn on the wire) retry until ``push_deadline_s``; a dead learner
        raises :class:`PeerLost` after ``heartbeat_grace_s``."""
        payload = pickle.dumps(segment, protocol=pickle.HIGHEST_PROTOCOL)
        crc = _crc(payload)
        deadline = time.monotonic() + self.push_deadline_s
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            # per-attempt fault application: a corrupted attempt is
            # rejected by the receiver's CRC and the NEXT attempt ships the
            # clean buffer — wire damage costs a retry, never a segment
            wire = fault_bytes("dcn.traj", payload)
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Sheeprl-CRC32": str(crc),
                "X-Sheeprl-Rank": str(self.rank),
                "X-Sheeprl-Meta": json.dumps(meta or {}),
            }
            try:
                status, _body, _ = self._request("/segment", wire, headers)
            except (urllib.error.URLError, ConnectionError, socket.timeout, OSError):
                self._note_failure()
                status = -1
            if status == 200:
                with self._lock:
                    self.segments_pushed += 1
                    self.push_retries += attempt - 1
                    self.push_wait_s += time.monotonic() - t0
                return
            if status == 409:
                with self._lock:
                    self.torn_rejected += 1
                err = b""
                try:
                    err = json.loads(_body or b"{}").get("error", "").encode()
                except Exception:
                    pass
                if b"crc" not in err:
                    # structurally torn (wrong segment shape): retrying the
                    # same buffer can never succeed — fail loudly NOW
                    raise TornTrajectory(err.decode() or "segment rejected by learner")
            if status == 410:
                raise ServiceStopped("learner front is gone (run finished)")
            if time.monotonic() > deadline:
                raise SegmentPushError(
                    f"segment undeliverable after {self.push_deadline_s:g}s "
                    f"({attempt} attempts, last status {status})"
                )
            time.sleep(0.05 if status in (409, 503) else 0.25)

    def fetch_params(self, have_version: int) -> Optional[Tuple[Any, int]]:
        """Newest ``(params, version)`` when the learner has something
        fresher than ``have_version`` (else None).  CRC-verified: a torn
        broadcast is counted and refetched, never applied."""
        try:
            status, body, headers = self._request(f"/params?have={int(have_version)}&rank={self.rank}")
        except (urllib.error.URLError, ConnectionError, socket.timeout, OSError):
            self._note_failure()
            return None
        if status != 200:
            return None
        want_crc = int(headers.get("X-Sheeprl-CRC32", "-1"))
        if _crc(body) != want_crc:
            with self._lock:
                self.fetch_crc_rejects += 1
            from sheeprl_tpu.telemetry import RECORDER

            RECORDER.record("dcn.torn_broadcast", rank=self.rank)
            return None
        with self._lock:
            self.fetches += 1
        return pickle.loads(body), int(headers.get("X-Sheeprl-Version", "0"))

    # -- control plane --------------------------------------------------------
    def poll(
        self,
        applied_version: int,
        *,
        latched: bool = False,
        hub: Optional[Dict[str, float]] = None,
    ) -> Optional[Dict[str, Any]]:
        body = json.dumps(
            {
                "rank": self.rank,
                "applied_version": int(applied_version),
                "latched": bool(latched),
                "hub": hub or {},
            }
        ).encode()
        try:
            status, resp, _ = self._request("/poll", body, {"Content-Type": "application/json"})
        except (urllib.error.URLError, ConnectionError, socket.timeout, OSError):
            self._note_failure()
            return None
        if status != 200:
            return None
        return json.loads(resp)

    def goodbye(self, reason: str = "") -> None:
        body = json.dumps({"rank": self.rank, "reason": reason}).encode()
        try:
            self._request("/goodbye", body, {"Content-Type": "application/json"})
        except Exception:
            pass

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            return {
                "Dcn/segments_pushed": float(self.segments_pushed),
                "Dcn/push_retries": float(self.push_retries),
                "Dcn/push_wait_s": float(self.push_wait_s),
                "Dcn/torn_rejected": float(self.torn_rejected),
                "Dcn/param_fetches": float(self.fetches),
                "Dcn/fetch_crc_rejects": float(self.fetch_crc_rejects),
            }
