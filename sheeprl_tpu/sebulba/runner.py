"""Shared Sebulba driver scaffolding (one copy for ppo.py and sac.py):
queue sizing, the env-worker fleet builder, the learner's segment-drain
loop, teardown, and the run-stats assembly."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.sebulba.actor import EnvWorker, WorkerSupervisor
from sheeprl_tpu.sebulba.queues import ObsQueue, ServiceStopped, TrajQueue
from sheeprl_tpu.telemetry.spans import SPANS, span
from sheeprl_tpu.utils.env import make_env, vectorize


class DrainPreempted(Exception):
    """The SIGTERM/SIGINT preemption latch fired while the learner was
    blocked on the trajectory queue.  The drivers catch this, run a final
    SYNCHRONOUS committed save, and exit cleanly — a preempted split run
    must not sit out the (up to 300 s) queue timeout eating into the
    preemption grace window, nor die mid-wait with its progress
    uncommitted."""


class StatsSink:
    """Thread-safe episode-stats funnel (workers push, the learner drains
    into the metric aggregator at log time).  BOUNDED: with
    ``metric.log_level=0`` nothing ever drains, and short-episode fused
    actors can finish millions of episodes per minute — the ring keeps the
    newest ``maxlen`` completions instead of growing for the run's life."""

    def __init__(self, maxlen: int = 65536) -> None:
        from collections import deque

        self._lock = threading.Lock()
        self._items: Any = deque(maxlen=maxlen)

    def __call__(self, items) -> None:
        with self._lock:
            self._items.extend(items)

    def drain(self) -> List[Tuple[float, int]]:
        with self._lock:
            out = list(self._items)
            self._items.clear()
            return out


def clamp_queue_slots(topo_cfg: Dict[str, Any], n_producers: int) -> int:
    """The trajectory ring must hold at least one segment per producer:
    the learner pops ``n_producers`` per update, so a smaller ring can
    NEVER satisfy it (producers block, the learner starves)."""
    slots = int(topo_cfg.get("traj_queue_slots", 4))
    if slots < n_producers:
        import warnings

        warnings.warn(
            f"topology.traj_queue_slots={slots} < {n_producers} producers: "
            "raising the ring to one segment per producer",
            RuntimeWarning,
        )
        slots = n_producers
    return slots


def build_worker_fleet(
    cfg: Any,
    topo_cfg: Dict[str, Any],
    *,
    protocol: Any,
    obs_queue: ObsQueue,
    traj_queue: TrajQueue,
    segment_steps: int,
    num_workers: int,
    envs_per_worker: int,
    log_dir: str,
    stop_event: threading.Event,
    stats_sink: Callable,
    env_offset: int = 0,
) -> WorkerSupervisor:
    """The env-worker fleet both drivers spawn: worker ``i`` owns env slice
    ``[i*envs_per_worker, (i+1)*envs_per_worker)`` built through the
    standard ``make_env``/``vectorize`` machinery; a respawn (bumped
    generation) reseeds the slice so the fresh worker's streams diverge
    from the deposed one's.

    ``env_offset`` shifts the whole fleet's slice within a LARGER global
    env space: a pod actor cell owns ``[offset, offset + num_workers *
    envs_per_worker)`` of the pod-wide ``env.num_envs``, so seeds and
    ``vector_env_idx`` stay globally unique across cells."""

    def spawn(worker_id: int, generation: int) -> EnvWorker:
        base = env_offset + worker_id * envs_per_worker
        seed = cfg.seed + base + 100003 * generation

        def env_builder(_seed=seed, _base=base):
            return vectorize(
                cfg,
                [
                    make_env(cfg, _seed + j, 0, run_name=log_dir, vector_env_idx=_base + j)
                    for j in range(envs_per_worker)
                ],
            )

        return EnvWorker(
            worker_id, env_builder, protocol, obs_queue, traj_queue,
            segment_steps, seed,
            timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
            stop_event=stop_event, stats_sink=stats_sink, generation=generation,
        )

    return WorkerSupervisor(
        spawn, num_workers,
        deadline_s=float(topo_cfg.get("worker_deadline_s", 120.0)),
        max_restarts=int(topo_cfg.get("max_worker_restarts", 3)),
    )


def drain_segments(
    traj_queue: TrajQueue,
    n: int,
    engines: List[Any],
    supervisor: Optional[WorkerSupervisor],
    preempted: Optional[Callable[[], bool]] = None,
) -> List[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """Pop ``n`` segments for one learner update, surfacing actor-engine
    failures and driving worker respawns while waiting — bounded by the
    queue's overall ``timeout_s`` so a wedged fused actor (which has no
    supervisor) fails the run loudly instead of hanging it.

    ``preempted`` (the drivers pass the checkpoint manager's rank-agreed
    latch) is polled between queue waits: a latched SIGTERM raises
    :class:`DrainPreempted` within one short wait (≤5 s) so the driver can
    depose the workers and exit through its final committed save."""
    deadline = time.monotonic() + traj_queue.timeout_s
    # the learner's queue wait is ITS OWN phase (telemetry/spans.py): time
    # spent here is actor starvation, not rollout work — the queue.wait
    # fraction of the phase breakdown is what traj_queue_slots tuning reads
    with span("queue.wait"):
        while True:
            if preempted is not None and preempted():
                raise DrainPreempted()
            try:
                return traj_queue.get_many(n, timeout_s=5.0)
            except TimeoutError:
                for eng in engines:
                    if eng.error is not None:
                        raise eng.error
                if supervisor is not None:
                    supervisor.check()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"trajectory queue produced < {n} segments in "
                        f"{traj_queue.timeout_s}s — actors wedged?"
                    )


def arm_preemption(cfg: Any) -> None:
    """Install the SIGTERM/SIGINT latch BEFORE the fleet starts: the
    cadence poll (``should_save``) only runs after a full drain+update, and
    a signal landing during the first (or any) queue wait must still be
    caught — :func:`drain_preemptible` polls the latch for the drivers."""
    from sheeprl_tpu.checkpoint import PREEMPTION_GUARD

    if cfg.checkpoint.get("save_on_preemption", True):
        PREEMPTION_GUARD.install()


def drain_preemptible(
    traj_queue: TrajQueue,
    n: int,
    engines: List[Any],
    supervisor: Optional[WorkerSupervisor],
    *,
    ckpt_mgr: Any,
    fabric: Any,
    policy_step: int,
    save_checkpoint: Callable[[], None],
) -> Optional[List[Tuple[Dict[str, Any], Dict[str, Any]]]]:
    """:func:`drain_segments` + the shared preemption exit (one copy for
    both drivers): a latch fired mid-wait runs the driver's final
    SYNCHRONOUS committed save (``ckpt_mgr.preempted`` forces the sync
    path) and returns ``None`` — the caller breaks out of its round loop
    and the normal teardown deposes the workers."""
    try:
        return drain_segments(
            traj_queue, n, engines, supervisor, preempted=lambda: ckpt_mgr.preempted
        )
    except DrainPreempted:
        fabric.print(
            f"Preemption latched mid-drain: final committed save at "
            f"step {policy_step}, exiting"
        )
        save_checkpoint()
        return None


def shutdown(
    stop_event: threading.Event,
    traj_queue: TrajQueue,
    obs_queue: Optional[ObsQueue],
    engines: List[Any],
    supervisor: Optional[WorkerSupervisor],
    join_timeout_s: float = 10.0,
) -> None:
    """Teardown in dependency order: stop flags → queues closed (pending
    inference requests failed so blocked workers unblock) → engines
    stopped → workers deposed and joined → engines joined."""
    stop_event.set()
    traj_queue.close()
    if obs_queue is not None:
        for req in obs_queue.close():
            req.fail(ServiceStopped("sebulba run finished"))
    for eng in engines:
        if hasattr(eng, "stop"):
            eng.stop()
    if supervisor is not None:
        supervisor.stop()
    for eng in engines:
        eng.join(join_timeout_s)


def collect_run_stats(
    *,
    topo: Any,
    updates: int,
    wall_s: float,
    env_steps: int,
    engines: List[Any],
    traj_queue: TrajQueue,
    broadcast: Any,
    traj_staleness_max: int,
    traj_staleness_sum: int,
    segments_consumed: int,
    supervisor: Optional[WorkerSupervisor],
) -> Dict[str, Any]:
    """The ``bench.py --mode sebulba`` stats contract, assembled once."""
    return {
        # the current span window's phase-breakdown fractions (queue.wait /
        # rollout / update.dispatch / param.broadcast / other, summing to
        # ~1.0) — bench.py republishes this as its `phase_breakdown` block
        "phase_breakdown": SPANS.breakdown(),
        "topology": topo.describe(),
        "updates": int(updates),
        "wall_s": wall_s,
        "env_steps": int(env_steps),
        "env_steps_per_s": env_steps / max(wall_s, 1e-9),
        "updates_per_s": updates / max(wall_s, 1e-9),
        "actor_idle_frac": float(np.mean([eng.actor_idle_frac() for eng in engines])),
        "queue_depth_frac": float(traj_queue.metrics()["Sebulba/queue_depth_frac"]),
        "param_staleness_max": int(broadcast.staleness_max),
        "traj_staleness_max": int(traj_staleness_max),
        "traj_staleness_avg": traj_staleness_sum / max(segments_consumed, 1),
        "actor_cache_sizes": [eng.cache_sizes() for eng in engines],
        "worker_restarts": supervisor.restarts if supervisor is not None else 0,
        "torn_rejected": traj_queue.torn_rejected,
    }
