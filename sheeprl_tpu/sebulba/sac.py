"""Sebulba SAC: the decoupled SAC loop rebuilt on the actor–learner device
split (``topology=sebulba``; docs/sebulba.md).

Same skeleton as :mod:`sheeprl_tpu.sebulba.ppo`, with the off-policy
differences:

* env workers push fixed-length **transition segments**
  (``topology.segment_steps`` × per-worker envs of ``obs/next_obs/actions/
  rewards/terminated`` rows) — the trajectory queue stays host-side
  (``stage=False``) because the learner's device-resident store is the
  :class:`~sheeprl_tpu.data.device_replay.DeviceReplay` HBM ring itself,
  sharded over the **learner sub-mesh**; the queue contributes ordering +
  backpressure + staleness metadata only;
* the learner appends consumed segments into the ring and runs the
  ``Ratio``-owed gradient steps through ``fused_uniform_train`` (sampling
  compiled into the update dispatch — PR 9's zero-copy path, now scoped to
  the learner device group);
* only the ACTOR subtree of the params is broadcast to the actor devices
  (the critic never leaves the learner group) — the Sebulba analogue of
  ``sac_decoupled``'s every-``sync_every``-windows weight refresh.

Workers take uniform random actions until their share of
``algo.learning_starts`` env steps is collected (the coupled loop's
prefill, decentralized per worker).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import make_sac_train_fns
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import (
    DeviceReplay,
    HostSpill,
    estimate_step_bytes,
    fit_hbm_window,
    fused_uniform_train,
    resolve_device_replay,
    update_chunks,
)
from sheeprl_tpu.parallel.topology import DeviceTopology, ParamBroadcast, topology_cfg
from sheeprl_tpu.sebulba.actor import ActorEngine, derive_ladder
from sheeprl_tpu.sebulba.queues import ObsQueue, TrajQueue
from sheeprl_tpu.sebulba.runner import (
    StatsSink,
    arm_preemption,
    build_worker_fleet,
    clamp_queue_slots,
    collect_run_stats,
    drain_preemptible,
    shutdown,
)
from sheeprl_tpu.utils.env import episode_stats, final_obs_rows, make_env, vectorize
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.optim import build_optimizer
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


class SACWorkerProtocol:
    """Per-step semantics of a SAC env worker: flattened-vector blocks out,
    tanh-squashed actions back; uniform random prefill until this worker's
    share of ``learning_starts`` is collected; ``next_obs`` rows carry the
    TRUE final observation on done envs (autoreset replaced them)."""

    def __init__(self, mlp_keys, act_space: gym.spaces.Box, prefill_steps: int):
        self.mlp_keys = tuple(mlp_keys)
        self.act_low = np.asarray(act_space.low, np.float32)
        self.act_high = np.asarray(act_space.high, np.float32)
        self.act_shape = act_space.shape
        self.prefill_steps = int(prefill_steps)

    def to_env_actions(self, a: np.ndarray) -> np.ndarray:
        return self.act_low + (a + 1.0) * 0.5 * (self.act_high - self.act_low)

    def _random_actions(self, rng: np.random.Generator, n: int) -> np.ndarray:
        env_actions = rng.uniform(self.act_low, self.act_high, (n,) + self.act_shape)
        span = self.act_high - self.act_low
        return np.clip(
            2.0 * (env_actions - self.act_low) / np.where(span == 0, 1, span) - 1.0, -1, 1
        ).astype(np.float32).reshape(n, -1)

    def on_reset(self, worker: EnvWorker, obs) -> None:
        worker._rng = np.random.default_rng(worker.seed)

    def run_segment(
        self, worker: EnvWorker, envs: Any, obs: Dict[str, np.ndarray], steps: int
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], List[Tuple[float, int]], int]:
        num_envs = envs.num_envs
        rows: Dict[str, List[np.ndarray]] = {
            k: [] for k in ("obs", "next_obs", "actions", "rewards", "terminated")
        }
        ep_stats: List[Tuple[float, int]] = []
        obs_vec = np.asarray(prepare_obs(obs, self.mlp_keys))
        for _ in range(steps):
            worker.beat()
            if worker.env_steps + len(rows["obs"]) * num_envs < self.prefill_steps:
                actions = self._random_actions(worker._rng, num_envs)
            else:
                out = worker.infer({"obs": obs_vec})
                actions = np.asarray(out["actions"]).reshape(num_envs, -1)
            next_obs, rewards, terminated, truncated, info = envs.step(
                self.to_env_actions(actions)
            )
            dones = np.logical_or(terminated, truncated).astype(np.float32)
            rewards = np.asarray(rewards, np.float32)
            next_vec = np.asarray(prepare_obs(next_obs, self.mlp_keys))
            store_next = next_vec
            done_idx = np.nonzero(dones)[0]
            if done_idx.size:
                final = final_obs_rows(info, done_idx, self.mlp_keys)
                if final is not None:
                    store_next = next_vec.copy()
                    store_next[done_idx] = np.concatenate(
                        [
                            np.asarray(final[k], np.float32).reshape(done_idx.size, -1)
                            for k in self.mlp_keys
                        ],
                        axis=-1,
                    )
            rows["obs"].append(obs_vec)
            rows["next_obs"].append(store_next)
            rows["actions"].append(actions.astype(np.float32))
            rows["rewards"].append(rewards.reshape(num_envs, 1))
            rows["terminated"].append(np.asarray(terminated, np.float32).reshape(num_envs, 1))
            obs_vec = next_vec
            obs = next_obs
            ep_stats.extend(episode_stats(info))
        segment = {k: np.stack(v, axis=0) for k, v in rows.items()}
        return obs, segment, ep_stats, steps * num_envs


def run_sebulba(fabric: Any, cfg: Any) -> Dict[str, Any]:
    """Train decoupled SAC through the Sebulba topology.  Returns a stats
    dict (throughput/queue/staleness counters) for ``bench.py``."""
    if fabric.num_processes > 1:
        # multi-process runs split actors and learner across HOSTS, not
        # devices: the in-process topology below assumes one device view
        from sheeprl_tpu.sebulba.pod import run_pod

        return run_pod(fabric, cfg)
    topo_cfg = topology_cfg(cfg)
    topo = DeviceTopology.from_config(fabric, cfg)
    learner_fab = topo.learner_fabric
    fabric.print(topo.describe())
    key = fabric.seed_everything(cfg.seed)

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    save_configs(cfg, log_dir)

    num_envs = int(cfg.env.num_envs)
    segment_steps = max(1, int(topo_cfg.get("segment_steps", 16)))
    num_workers = max(1, int(topo_cfg.get("env_workers", 2)))
    if num_envs % num_workers:
        raise ValueError(
            f"sebulba env workers need env.num_envs ({num_envs}) divisible "
            f"by topology.env_workers ({num_workers})"
        )
    envs_per_worker = num_envs // num_workers

    probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only, like the reference")
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    for k in mlp_keys:
        if k not in obs_space.spaces:
            raise ValueError(f"mlp key '{k}' not in observation space {list(obs_space.spaces)}")
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))

    # ---------------- learner: agent + train program -------------------------
    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        key = jnp.asarray(state["key"])
    actor, critic, params = build_agent(learner_fab, act_dim, cfg, obs_dim, state.get("agent"))
    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)
    opt_state = learner_fab.replicate(
        state.get("opt_state")
        or {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    def plain_apply(critic_mod, cp, o, a, k):
        return critic_mod.apply(cp, o, a)

    act_fn, train_phase = make_sac_train_fns(
        actor, critic, plain_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )

    # ---------------- device-resident replay on the learner sub-mesh ---------
    capacity = int(cfg.buffer.size) // num_envs
    memmap_dir = os.path.join(log_dir, "memmap_buffer", "rank_0") if cfg.buffer.memmap else None
    use_device_replay = resolve_device_replay(cfg, fabric.accelerator)
    if use_device_replay:
        step_bytes = estimate_step_bytes(
            obs_space, mlp_keys, extra_bytes=4 * (act_dim + 2), copies_per_key=2
        )
        hbm_window, spill_needed = fit_hbm_window(
            capacity, num_envs, step_bytes, cfg.buffer.get("hbm_window")
        )
        spill = (
            HostSpill(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
            if spill_needed
            else None
        )
        rb: Any = DeviceReplay(
            hbm_window, num_envs, mesh=learner_fab.mesh, data_axis=learner_fab.data_axis, spill=spill
        )
    else:
        rb = ReplayBuffer(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    batch_size = int(cfg.algo.per_rank_batch_size) * learner_fab.local_world_size
    train_phase_dev = None
    if use_device_replay:
        def _prep_batch(b):
            return {
                "obs": b["obs"],
                "next_obs": b["next_obs"],
                "actions": b["actions"],
                "rewards": b["rewards"][..., 0],
                "terminated": b["terminated"][..., 0],
            }

        train_phase_dev = fused_uniform_train(
            learner_fab,
            train_phase,
            rb,
            batch_size,
            _prep_batch,
            name=f"{cfg.algo.name}.sebulba_train_phase_device",
            max_recompiles=cfg.algo.get("max_recompiles"),
        )

    # ---------------- broadcast + queues + actors ----------------------------
    broadcast = ParamBroadcast(
        fabric,
        topo.actor_devices,
        extract=lambda p: p["actor"],
        max_staleness=int(topo_cfg.get("max_staleness", 2)),
        gate_timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    sync_every = max(1, int(topo_cfg.get("sync_every", 1)))
    traj_queue = TrajQueue(
        clamp_queue_slots(topo_cfg, num_workers),
        segment_steps,
        learner_fab,
        stage=False,  # the device-resident store is the DeviceReplay ring
        timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    stats_sink = StatsSink()
    stop_event = threading.Event()
    obs_queue = ObsQueue(max_pending=2 * num_workers)
    ladder = derive_ladder(envs_per_worker, num_workers, topo_cfg.get("actor_batch_ladder"))

    def policy_fn(p, obs, k):
        a, k_next = act_fn.jitted(p, obs["obs"], k)
        return {"actions": a}, k_next

    obs_spec = {"obs": ((obs_dim,), np.dtype(np.float32))}
    actor_param_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params["actor"]
    )
    engines: List[ActorEngine] = []
    for i, dev in enumerate(topo.actor_devices):
        eng = ActorEngine(
            i, dev, policy_fn, obs_spec, actor_param_spec, ladder, envs_per_worker,
            obs_queue, broadcast, jax.random.fold_in(key, 0xF0 + i),
            max_wait_s=float(topo_cfg.get("max_wait_ms", 20.0)) / 1e3,
            max_recompiles=cfg.algo.get("max_recompiles"),
        )
        if cfg.algo.get("compile_warmup", True):
            eng.warmup(fabric.compile_pool, join=False)
        engines.append(eng)
    fabric.compile_pool.join()

    learning_starts = int(cfg.algo.learning_starts) if not cfg.dry_run else 0
    protocol = SACWorkerProtocol(
        mlp_keys, act_space, prefill_steps=-(-learning_starts // num_workers)
    )

    supervisor = build_worker_fleet(
        cfg, topo_cfg,
        protocol=protocol, obs_queue=obs_queue, traj_queue=traj_queue,
        segment_steps=segment_steps, num_workers=num_workers,
        envs_per_worker=envs_per_worker, log_dir=log_dir,
        stop_event=stop_event, stats_sink=stats_sink,
    )

    # ---------------- counters -----------------------------------------------
    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)
    steps_per_round = num_envs * segment_steps
    total_rounds = max(int(cfg.algo.total_steps) // steps_per_round, 1)
    if cfg.dry_run:
        total_rounds = 1
    start_round = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    windows = int(state.get("windows", 0))
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    staleness_sum = 0
    staleness_max = 0
    segments_consumed = 0
    env_steps_consumed = 0
    last_losses = None
    counter_dev = None
    t_start = time.perf_counter()

    # ---------------- run ----------------------------------------------------
    # live hub sources for the run + a fresh span window so the end-of-run
    # phase breakdown covers the training loop (see sebulba/ppo.py)
    from sheeprl_tpu.telemetry import HUB, SPANS

    HUB.register("sebulba.traj_queue", traj_queue.metrics)
    HUB.register("sebulba.broadcast", broadcast.metrics)
    SPANS.roll_window()

    arm_preemption(cfg)

    def save_checkpoint() -> None:
        # closure over the live loop variables: the cadence save and the
        # preemption final save must write the identical state
        fabric.call(
            "on_checkpoint_player",
            ckpt_path=os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt"),
            state={
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "update": rnd,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "grad_steps": grad_step_counter,
                "windows": windows,
            },
            replay_buffer=rb if cfg.buffer.checkpoint else None,
        )

    try:
        # inside the try: the first publish crosses fabric.copy_to (a
        # fault-injection site) — a throw here must still unregister
        broadcast.publish(params, version=windows)
        for eng in engines:
            eng.start()
        supervisor.start()
        rnd = start_round - 1
        for rnd in range(start_round, total_rounds + 1):
            with timer("Time/env_interaction_time"):
                items = drain_preemptible(
                    traj_queue, num_workers, engines, supervisor,
                    ckpt_mgr=ckpt_mgr, fabric=fabric, policy_step=policy_step,
                    save_checkpoint=save_checkpoint,
                )
            if items is None:  # preempted mid-wait: committed save done
                break
            for seg, meta in items:
                base = int(meta.get("worker", 0)) * envs_per_worker
                rb.add(
                    {k: np.asarray(v) for k, v in seg.items()},
                    indices=range(base, base + envs_per_worker),
                )
                lag = broadcast.version - int(meta.get("version", 0))
                staleness_sum += lag
                staleness_max = max(staleness_max, lag)
                env_steps_consumed += int(meta.get("env_steps", 0))
            segments_consumed += len(items)
            policy_step += steps_per_round

            if policy_step >= learning_starts:
                gradient_steps = ratio(policy_step / learner_fab.world_size)
                if gradient_steps > 0:
                    windows += 1
                    with timer("Time/train_time"):
                        if train_phase_dev is not None:
                            if counter_dev is None:
                                counter_dev = learner_fab.replicate(np.int32(grad_step_counter))
                            for u in update_chunks(
                                gradient_steps,
                                bytes_per_update=rb.sampled_bytes_per_update(batch_size),
                            ):
                                key, tk = jax.random.split(key)
                                params, opt_state, counter_dev, last_losses = train_phase_dev(
                                    params, opt_state, rb.buffers, rb.cursor, tk,
                                    counter_dev, n_samples=u,
                                )
                                grad_step_counter += u
                        else:
                            sample = rb.sample(batch_size, n_samples=gradient_steps)
                            batches = {
                                "obs": jnp.asarray(sample["obs"]),
                                "next_obs": jnp.asarray(sample["next_obs"]),
                                "actions": jnp.asarray(sample["actions"]),
                                "rewards": jnp.asarray(sample["rewards"][..., 0]),
                                "terminated": jnp.asarray(sample["terminated"][..., 0]),
                            }
                            batches = learner_fab.shard_batch(batches, axis=1)
                            key, tk = jax.random.split(key)
                            params, opt_state, last_losses = train_phase(
                                params, opt_state, batches, tk, jnp.int32(grad_step_counter)
                            )
                            grad_step_counter += gradient_steps
                    if windows % sync_every == 0:
                        broadcast.publish(params, version=windows)
                        broadcast.gate()
            supervisor.check()

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or rnd == total_rounds or cfg.dry_run
            ):
                for ep_ret, ep_len in stats_sink.drain():
                    aggregator.update("Rewards/rew_avg", float(ep_ret))
                    aggregator.update("Game/ep_len_avg", int(ep_len))
                if last_losses is not None:
                    vl, pl, al = last_losses
                    aggregator.update("Loss/value_loss", vl)
                    aggregator.update("Loss/policy_loss", pl)
                    aggregator.update("Loss/alpha_loss", al)
                extra = dict(traj_queue.metrics())
                extra.update(broadcast.metrics())
                extra["Sebulba/traj_staleness_max"] = float(staleness_max)
                extra["Sebulba/traj_staleness_avg"] = staleness_sum / max(segments_consumed, 1)
                extra["Sebulba/actor_idle_frac"] = float(
                    np.mean([eng.actor_idle_frac() for eng in engines])
                )
                extra["Params/replay_ratio"] = (
                    grad_step_counter * learner_fab.world_size / max(policy_step, 1)
                )
                last_log = flush_metrics(
                    aggregator, timer, logger, policy_step, last_log, extra_metrics=extra
                )

            if ckpt_mgr.should_save(policy_step, last_checkpoint, final=rnd == total_rounds):
                last_checkpoint = policy_step
                save_checkpoint()
            if ckpt_mgr.preempted:
                fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
                break
    finally:
        # unregister on EVERY exit — a leaked source would pin the dead
        # run's queue ring and report stale gauges into the next run
        HUB.unregister("sebulba.traj_queue")
        HUB.unregister("sebulba.broadcast")
        shutdown(stop_event, traj_queue, obs_queue, engines, supervisor)

    run_stats = collect_run_stats(
        topo=topo, updates=windows,
        wall_s=time.perf_counter() - t_start, env_steps=env_steps_consumed,
        engines=engines, traj_queue=traj_queue, broadcast=broadcast,
        traj_staleness_max=staleness_max, traj_staleness_sum=staleness_sum,
        segments_consumed=segments_consumed, supervisor=supervisor,
    )

    if getattr(rb, "spill", None) is not None:
        rb.spill.close()
    ckpt_mgr.finalize()
    if cfg.algo.run_test and not ckpt_mgr.preempted:
        test(actor, fabric.to_host(params["actor"]), cfg, log_dir, logger)
    if logger is not None:
        logger.close()
    return run_stats
